"""Flight recorder: bounded span tracing + an OpenMetrics surface for the
streaming engine.

``engine/stats.py`` answers *how much* (counters, a bounded per-step ring);
this module answers *which batch and where*: every submitted batch gets a
**trace id** at ``submit()`` and the dispatcher stamps every stage of that
batch's journey — queue wait, coalesce (the megabatch span LINKS the submit
spans it absorbed), pad, AOT lookup (hit vs compile), device step, watchdog
sync, retry/backoff, rollback, kernel demotion, quarantine, boundary merge,
snapshot write/restore — as a span in a capacity-bounded, thread-safe ring.
Every :data:`~metrics_tpu.engine.faults.FAULT_SITES` firing becomes a span
event, so a chaos trace shows WHERE each injected failure landed in the
pipeline, not just that it was counted.

Contracts (mirroring the PR-6 fault layer):

* **Off ⇒ free.** The engine consults ``EngineConfig.trace`` with one
  ``is not None`` check per site; no recorder means no work on the hot path
  (guarded by the ``obs_overhead`` bench entry).
* **Bounded.** The span ring holds ``capacity`` records; older spans are
  dropped (counted in :attr:`TraceRecorder.dropped`), never grown.
* **Occurrence-deterministic.** Trace ids come from a submit-ordered counter
  (``t1, t2, …``) and a megabatch's id derives from its first member
  (``g<k>``) — never from wall time or thread ids — so two same-seed chaos
  runs produce IDENTICAL :meth:`canonical_sequence` outputs (timestamps and
  durations are excluded from the canonical form; span *args* carry only
  deterministic values by construction). ``make obs-smoke`` asserts this.

Two exporters:

* :meth:`TraceRecorder.to_chrome_trace` — Chrome/Perfetto trace-event JSON
  (load at https://ui.perfetto.dev): host threads as named tracks, spans as
  complete ("X") events, fault firings as instants, and flow arrows from
  each submit span to the megabatch that absorbed it.
  ``StreamingEngine.export_trace(path)`` writes it. For REAL device
  timelines on TPU, wrap the traffic in :func:`device_trace_session` — the
  ``step`` arg on every ``device_step`` span is the correlation key into the
  ``jax.profiler`` trace (docs/observability.md shows the workflow, after
  "Scalable Training of Language Models using JAX pjit and TPUv4"'s
  host/device timeline correlation).
* :func:`render_openmetrics` — an OpenMetrics/Prometheus text snapshot
  (``StreamingEngine.metrics_text()``): the engine's lifetime counters plus
  REAL fixed-bucket latency histograms (step/queue/result/merge). The
  histograms dogfood the library's own ``histogram_accumulate`` path on host
  numpy: observations buffer as raw values and the bucket counts are folded
  by the same fused bincount the served metrics use
  (:class:`FixedBucketHistogram`).
"""
import json
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "DEFAULT_LATENCY_BUCKETS_US",
    "FixedBucketHistogram",
    "TraceRecorder",
    "device_trace_session",
    "render_openmetrics",
]

#: Default latency bucket upper bounds, in microseconds (µs). Spanning 50 µs
#: (a warm dispatch) to 1 s (a compile or a watchdog expiry) in roughly
#: 1-2.5-5 decades — the fixed-bucket shape Prometheus histograms want.
DEFAULT_LATENCY_BUCKETS_US = (
    50.0, 100.0, 250.0, 500.0, 1_000.0, 2_500.0, 5_000.0, 10_000.0,
    25_000.0, 50_000.0, 100_000.0, 250_000.0, 500_000.0, 1_000_000.0,
)

#: The reserved trace id for engine-level (not batch-bound) spans and events:
#: boundary merges, result computes, snapshot write/restore, fault firings.
ENGINE_TRACE = "engine"


class FixedBucketHistogram:
    """A Prometheus-style fixed-bucket histogram over host observations.

    Observations buffer as raw values; :meth:`flush` folds them into the
    cumulative bucket counts via the library's own ``histogram_accumulate``
    (``metrics_tpu/ops/kernels``) on host numpy — the dogfooding contract:
    the observability surface is served by the same fused bincount path the
    metrics themselves use. ``observe`` is an amortized-O(1) append (hot-path
    safe); folds run at render/boundary time, or inline once per
    :attr:`FOLD_PENDING_AT` observations so an engine that is never scraped
    stays memory-bounded.
    """

    def __init__(self, name: str, edges: Sequence[float] = DEFAULT_LATENCY_BUCKETS_US):
        edges = tuple(float(e) for e in edges)
        if not edges or any(b <= a for a, b in zip(edges, edges[1:])):
            raise ValueError(f"histogram edges must be non-empty ascending, got {edges}")
        self.name = name
        self.edges = edges
        # guards _pending/_counts/_sum/_n with SHORT critical sections only:
        # the dispatcher observes while a scrape thread reads (metrics_text/
        # summary run flush WITHOUT the recorder lock, and TraceRecorder
        # .observe resolves the histogram under the recorder lock but
        # observes AFTER releasing it — no lock ever nests another). The jax
        # fold itself runs under _fold_lock with _lock RELEASED, so a
        # scrape's fold (first call pays a jit compile) can never block the
        # dispatcher's observe() — that is the "observe is hot-path safe"
        # contract
        self._lock = threading.Lock()
        self._fold_lock = threading.Lock()  # serializes folds; never inside _lock
        self._counts = np.zeros(len(edges) + 1, np.int64)  # [+Inf overflow last]
        self._sum = 0.0
        self._n = 0
        self._pending: List[float] = []

    #: Pending observations that trigger an inline fold: keeps an engine that
    #: is never scraped memory-BOUNDED (the span ring next door is capacity-
    #: bounded; the histogram buffer must be too). Folds amortize to O(1)
    #: per observe, and the pad-to-pow2 below means the triggered fold always
    #: reuses one compiled shape.
    FOLD_PENDING_AT = 4096

    def observe(self, value: float) -> None:
        with self._lock:
            self._pending.append(float(value))
            overflow = len(self._pending) >= self.FOLD_PENDING_AT
        if overflow:
            # non-blocking: if a scrape is folding RIGHT NOW it already swapped
            # our backlog out, and waiting on its jax dispatch would stall the
            # hot path — the freshly-appended tail rides the next fold
            self._flush(blocking=False)

    def flush(self) -> None:
        """Fold pending observations into the cumulative counts (dogfooded
        through ``histogram_accumulate``'s fixed-length bincount).

        The fold runs OUTSIDE ``_lock`` (under ``_fold_lock``): a concurrent
        ``observe`` appends to the fresh pending list and never waits out the
        jax dispatch. Nothing is lost or double-counted — pending is swapped
        out atomically, and the folded delta merges back under ``_lock``."""
        self._flush(blocking=True)

    def _flush(self, blocking: bool) -> None:
        if blocking:
            self._fold_lock.acquire()
        elif not self._fold_lock.acquire(blocking=False):
            return
        try:
            self._flush_under_fold_lock()
        finally:
            self._fold_lock.release()

    def _flush_under_fold_lock(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            return
        import jax

        from metrics_tpu.ops.kernels import histogram_accumulate

        vals = np.asarray(pending, np.float64)
        # bucket k holds v <= edges[k]; v above every edge lands in +Inf
        idx = np.searchsorted(np.asarray(self.edges), vals, side="left").astype(np.int32)
        length = len(self.edges) + 1
        # pad to the next power of two with out-of-range indices (>= length
        # DROPS, per bincount semantics): distinct fold shapes — hence XLA
        # retraces — stay O(log n) however scrape cadence slices the stream,
        # and the FOLD_PENDING_AT-triggered fold always reuses one shape
        n_pad = 1 << max(0, (idx.size - 1).bit_length())
        padded = np.full(n_pad, length, np.int32)
        padded[: idx.size] = idx
        # the fold is HOST work: pin it to the CPU backend so a metrics
        # scrape never launches device ops interleaved with serving steps.
        # LOCAL devices only — under jax.distributed (ISSUE 15's fleet)
        # jax.devices() is the GLOBAL list whose first entry belongs to
        # process 0, and a scrape on any other host would try to fold onto
        # a non-addressable device
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            counts = np.asarray(histogram_accumulate(padded, length=length))
        with self._lock:
            self._counts += counts
            self._sum += float(vals.sum())
            self._n += int(vals.size)

    @property
    def count(self) -> int:
        self.flush()
        with self._lock:
            return int(self._n)

    @property
    def sum(self) -> float:
        self.flush()
        with self._lock:
            return float(self._sum)

    def bucket_counts(self) -> np.ndarray:
        """Per-bucket (non-cumulative) counts; last entry is the +Inf bucket."""
        self.flush()
        with self._lock:
            return self._counts.copy()

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile over the folded counts: the upper edge
        of the bucket where the cumulative count crosses ``q`` (the +Inf
        bucket reports the last finite edge — a floor, never an invented
        value). 0.0 when empty. This is the overload detector's
        queue-residency feed (``engine/admission.py``): watermark tests only
        need bucket resolution, and the folded counts are the cheapest
        consistent view the recorder has."""
        counts = self.bucket_counts()
        total = int(counts.sum())
        if total == 0:
            return 0.0
        target = float(q) * total
        cum = 0
        for i, c in enumerate(counts):
            cum += int(c)
            if cum >= target:
                return float(self.edges[min(i, len(self.edges) - 1)])
        return float(self.edges[-1])

    def snapshot(self) -> Dict[str, Any]:
        self.flush()
        with self._lock:
            return {
                "count": int(self._n),
                "sum": round(float(self._sum), 1),
                "le": list(self.edges),
                "counts": [int(c) for c in self._counts],
            }


def _fmt_num(v: Any) -> str:
    if isinstance(v, float):
        return format(v, ".17g")
    return str(int(v))


def render_openmetrics(
    counters: Dict[str, Any],
    histograms: Iterable[FixedBucketHistogram] = (),
    labeled_counters: Optional[Dict[str, Tuple[str, Dict[str, int]]]] = None,
    gauges: Optional[Dict[str, Any]] = None,
    prefix: str = "metrics_tpu_engine_",
) -> str:
    """Render one OpenMetrics text exposition.

    ``counters`` maps family name (WITHOUT the ``_total`` suffix — it is
    appended per the OpenMetrics counter-sample rule) to value;
    ``labeled_counters`` maps family name to ``(label_name, {label: value})``;
    ``histograms`` render with cumulative ``_bucket{le=...}`` samples plus
    ``_sum``/``_count``. Ends with the mandatory ``# EOF``.
    """
    lines: List[str] = []
    for name in sorted(counters):
        full = prefix + name
        lines.append(f"# TYPE {full} counter")
        lines.append(f"{full}_total {_fmt_num(counters[name])}")
    for name in sorted(labeled_counters or {}):
        label, values = (labeled_counters or {})[name]
        full = prefix + name
        lines.append(f"# TYPE {full} counter")
        for key in sorted(values):
            lines.append(f'{full}_total{{{label}="{key}"}} {_fmt_num(values[key])}')
    for name in sorted(gauges or {}):
        full = prefix + name
        lines.append(f"# TYPE {full} gauge")
        lines.append(f"{full} {_fmt_num((gauges or {})[name])}")
    for hist in histograms:
        full = prefix + hist.name
        # ONE atomic snapshot per histogram: separate bucket/sum/count reads
        # could interleave with a concurrent observe and break the
        # count-equals-+Inf-bucket invariant the parser validates
        snap = hist.snapshot()
        lines.append(f"# TYPE {full} histogram")
        cum = 0
        for edge, n in zip(snap["le"], snap["counts"][:-1]):
            cum += int(n)
            lines.append(f'{full}_bucket{{le="{format(edge, "g")}"}} {cum}')
        cum += int(snap["counts"][-1])
        lines.append(f'{full}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{full}_sum {_fmt_num(float(snap['sum']))}")
        lines.append(f"{full}_count {snap['count']}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def _track_label() -> str:
    """A STABLE track label for the calling thread: the dispatcher thread's
    fixed name maps to ``dispatcher``; everything else keeps its thread name
    (``MainThread`` for the typical producer/reader)."""
    name = threading.current_thread().name
    return "dispatcher" if name == "metrics-tpu-engine" else name


class TraceRecorder:
    """Bounded, thread-safe span/event ring with deterministic trace ids.

    One recorder may be shared by several engines (the chaos smoke does):
    the ring, the trace-id counter, and the histograms are all lock-guarded.
    Spans are recorded at END (an abandoned ``begin`` leaves no record);
    events are instantaneous. Timestamps are µs since recorder creation.
    """

    def __init__(
        self,
        capacity: int = 8192,
        latency_buckets_us: Sequence[float] = DEFAULT_LATENCY_BUCKETS_US,
    ):
        if capacity <= 0:
            raise ValueError(f"trace capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: "deque[Dict[str, Any]]" = deque()
        self._dropped = 0
        self._n_traces = 0
        self._t0 = time.perf_counter()
        # kept for lazy creation in observe(): a histogram first seen there
        # must carry the recorder's configured edges, not the defaults
        self._latency_buckets = tuple(float(e) for e in latency_buckets_us)
        self._hists: Dict[str, FixedBucketHistogram] = {
            name: FixedBucketHistogram(name, self._latency_buckets)
            for name in ("step_latency_us", "queue_wait_us", "result_latency_us", "merge_latency_us")
        }

    # ------------------------------------------------------------- trace ids

    def new_trace(self) -> str:
        """A fresh trace id from the submit-ordered counter (``t<N>``) —
        deterministic as long as allocation order is (single producer)."""
        with self._lock:
            self._n_traces += 1
            return f"t{self._n_traces}"

    @staticmethod
    def group_trace(links: Sequence[str]) -> str:
        """The megabatch trace id DERIVED from its first absorbed submit
        (``t7 → g7``): deterministic under any producer/dispatcher timing,
        because groups partition the submit stream."""
        for tid in links:
            if tid:
                return "g" + tid.lstrip("tg")
        return ENGINE_TRACE

    # ------------------------------------------------------------- recording

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._ring) >= self.capacity:
                self._ring.popleft()
                self._dropped += 1
            self._ring.append(rec)

    def begin(self, name: str, trace: str, track: Optional[str] = None, **args: Any) -> List[Any]:
        """Open a span; returns the handle :meth:`end` closes. Nothing is
        recorded until ``end`` — a span abandoned mid-failure leaves no
        half-open record in the ring."""
        return [name, trace, track or _track_label(), time.perf_counter(), args]

    def end(self, handle: List[Any], **more_args: Any) -> float:
        """Close a span; returns its duration in µs (so callers feeding a
        latency histogram never reach into the handle's layout)."""
        name, trace, track, t0, args = handle
        if more_args:
            args = {**args, **more_args}
        dur_us = (time.perf_counter() - t0) * 1e6
        self._append({
            "kind": "span", "name": name, "trace": trace, "track": track,
            "ts": (t0 - self._t0) * 1e6, "dur": dur_us, "args": args,
        })
        return dur_us

    def complete(
        self, name: str, trace: str, dur_us: float, track: Optional[str] = None, **args: Any
    ) -> None:
        """Record an already-measured span retroactively (e.g. queue wait:
        the duration was observed before the recorder was consulted)."""
        now_us = self._now_us()
        self._append({
            "kind": "span", "name": name, "trace": trace,
            "track": track or _track_label(),
            "ts": now_us - float(dur_us), "dur": float(dur_us), "args": args,
        })

    def event(self, name: str, trace: str = ENGINE_TRACE, track: Optional[str] = None, **args: Any) -> None:
        """An instantaneous event (fault firings, retries, rollbacks)."""
        self._append({
            "kind": "event", "name": name, "trace": trace,
            "track": track or _track_label(), "ts": self._now_us(), "args": args,
        })

    def observe(self, hist: str, value_us: float) -> None:
        """One latency observation into the named fixed-bucket histogram."""
        with self._lock:
            h = self._hists.get(hist)
            if h is None:
                h = self._hists[hist] = FixedBucketHistogram(hist, self._latency_buckets)
        # observe OUTSIDE the recorder lock: a scrape thread holds the
        # histogram lock across its flush's jax fold, and blocking on it
        # while holding the recorder lock would stall every producer's
        # submit (new_trace/_append need the recorder lock) for the whole
        # fold — the two locks must never nest
        h.observe(value_us)

    # --------------------------------------------------------------- reading

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def records(self) -> List[Dict[str, Any]]:
        """A snapshot of the ring, oldest first. Shallow — records are
        append-only and never mutated after :meth:`_append`, and a deep copy
        here would stall the dispatcher's span appends (same lock) for the
        whole ring on every telemetry scrape."""
        with self._lock:
            return list(self._ring)

    def spans(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        return [r for r in self.records() if r["kind"] == "span" and (name is None or r["name"] == name)]

    def events(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        return [r for r in self.records() if r["kind"] == "event" and (name is None or r["name"] == name)]

    def fault_sites(self) -> Dict[str, int]:
        """Injected-fault firings by site, from the recorded ``fault`` events
        (the chaos smokes assert this covers every ``FAULT_SITES`` entry)."""
        out: Dict[str, int] = {}
        for e in self.events("fault"):
            site = e["args"].get("site")
            if site:
                out[site] = out.get(site, 0) + 1
        return out

    def histograms(self) -> List[FixedBucketHistogram]:
        with self._lock:
            return list(self._hists.values())

    # --------------------------------------------------------- canonical form

    @staticmethod
    def _canon_value(v: Any) -> Any:
        if isinstance(v, (list, tuple)):
            return tuple(TraceRecorder._canon_value(x) for x in v)
        if isinstance(v, np.generic):
            return v.item()
        return v

    def canonical_sequence(self) -> Dict[str, List[Tuple]]:
        """The determinism observable: per-track ordered ``(kind, name,
        trace, sorted-args)`` tuples, timestamps and durations EXCLUDED (span
        args carry only occurrence-deterministic values by construction).
        Two same-seed chaos runs must compare equal — provided nothing was
        dropped from the ring (assert :attr:`dropped` == 0 alongside)."""
        out: Dict[str, List[Tuple]] = {}
        for r in self.records():
            canon = (
                r["kind"], r["name"], r["trace"],
                tuple(sorted((k, self._canon_value(v)) for k, v in r["args"].items())),
            )
            out.setdefault(r["track"], []).append(canon)
        return out

    # ---------------------------------------------------------------- export

    def to_chrome_trace(self) -> Dict[str, Any]:
        """The Chrome/Perfetto trace-event document: spans as complete
        (``X``) events on named per-track threads, fault firings as instants,
        and flow arrows (``s``/``f``) from each submit span into the
        megabatch span that absorbed it (the coalesce links, drawable)."""
        records = self.records()
        tracks: List[str] = []
        for r in records:
            if r["track"] not in tracks:
                tracks.append(r["track"])
        # stable presentation: dispatcher first, then alphabetical
        tracks.sort(key=lambda t: (t != "dispatcher", t))
        tid = {t: i + 1 for i, t in enumerate(tracks)}
        events: List[Dict[str, Any]] = [
            {
                "ph": "M", "name": "thread_name", "pid": 1, "tid": tid[t], "ts": 0,
                "args": {"name": t},
            }
            for t in tracks
        ]
        submit_at: Dict[str, Tuple[int, float]] = {}
        for r in records:
            if r["kind"] == "span" and r["name"] == "submit":
                submit_at[r["trace"]] = (tid[r["track"]], r["ts"])
        flow_n = 0
        for r in records:
            base = {"name": r["name"], "cat": "engine", "pid": 1, "tid": tid[r["track"]],
                    "ts": round(r["ts"], 3)}
            args = {"trace": r["trace"], **r["args"]}
            if r["kind"] == "span":
                events.append({**base, "ph": "X", "dur": round(r["dur"], 3), "args": args})
                for link in r["args"].get("links", ()):  # coalesce → submit flows
                    src = submit_at.get(link)
                    if src is None:
                        continue
                    flow_n += 1
                    events.append({
                        "ph": "s", "id": flow_n, "name": "batch", "cat": "flow",
                        "pid": 1, "tid": src[0], "ts": round(src[1], 3),
                    })
                    events.append({
                        "ph": "f", "bp": "e", "id": flow_n, "name": "batch", "cat": "flow",
                        "pid": 1, "tid": tid[r["track"]], "ts": round(r["ts"], 3),
                    })
            else:
                events.append({**base, "ph": "i", "s": "t", "args": args})
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "generator": "metrics_tpu.engine.trace",
                "spans_dropped": self.dropped,
            },
        }

    def export(self, path: str) -> str:
        """Write :meth:`to_chrome_trace` as JSON (``out/trace_*.json`` by the
        repo's sidecar-hygiene convention — ``out/`` is gitignored)."""
        import os

        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f, indent=1)
        return path

    # --------------------------------------------------------------- summary

    def summary(self, slowest: int = 5) -> Dict[str, Any]:
        """The trace/SLO block ``tools/engine_report.py`` renders: span and
        drop totals, per-name duration aggregates, histogram snapshots, and
        the slowest-N traces with their per-span breakdown (the causal answer
        to "which batch produced the tail"). The end-to-end definition (root
        span + queue waits) is mirrored by ``tools/trace_export.summarize``
        on exported documents — change one and the parity pin in
        ``tests/engine/test_trace.py`` goes red."""
        records = self.records()
        spans = [r for r in records if r["kind"] == "span"]
        by_name: Dict[str, Dict[str, Any]] = {}
        by_trace: Dict[str, List[Dict[str, Any]]] = {}
        for s in spans:
            agg = by_name.setdefault(s["name"], {"count": 0, "dur_us_total": 0.0, "dur_us_max": 0.0})
            agg["count"] += 1
            agg["dur_us_total"] += s["dur"]
            agg["dur_us_max"] = max(agg["dur_us_max"], s["dur"])
            by_trace.setdefault(s["trace"], []).append(s)
        roots = []
        for trace, members in by_trace.items():
            if trace == ENGINE_TRACE:
                continue
            # the megabatch span is the trace's root when present; its wall
            # time plus the (non-overlapping) queue wait is the batch
            # journey's end-to-end latency — the tail the SLO cares about.
            # A submit-ONLY trace is no journey: its batch's journey lives in
            # the g-trace that absorbed it (linked, and its blocked-put wait
            # is already inside that trace's queue_wait) — ranking it here
            # would double-count backpressure and crowd out real tails
            root = next((s for s in members if s["name"] == "coalesce"), None)
            if root is None:
                non_submit = [s for s in members if s["name"] != "submit"]
                if not non_submit:
                    continue
                root = max(non_submit, key=lambda s: s["dur"])
            total = root["dur"] + sum(s["dur"] for s in members if s["name"] == "queue_wait")
            roots.append((total, root, members))
        roots.sort(key=lambda rm: -rm[0])
        slowest_traces = []
        for total, root, members in roots[: max(0, int(slowest))]:
            breakdown: Dict[str, float] = {}
            for s in members:
                if s is not root:
                    breakdown[s["name"]] = round(breakdown.get(s["name"], 0.0) + s["dur"], 1)
            entry: Dict[str, Any] = {
                "trace": root["trace"],
                "root": root["name"],
                "dur_us": round(total, 1),
                "n_spans": len(members),
                "breakdown": breakdown,
            }
            links = root["args"].get("links")
            if links:
                entry["links"] = list(links)
            if "stream_ids" in root["args"]:
                entry["stream_ids"] = list(root["args"]["stream_ids"])
            slowest_traces.append(entry)
        return {
            "spans": len(spans),
            "events": len(records) - len(spans),
            "dropped": self.dropped,
            "capacity": self.capacity,
            "by_name": {
                k: {"count": v["count"], "dur_us_total": round(v["dur_us_total"], 1),
                    "dur_us_max": round(v["dur_us_max"], 1)}
                for k, v in sorted(by_name.items())
            },
            "histograms": {h.name: h.snapshot() for h in self.histograms() if h.count},
            "slowest_traces": slowest_traces,
        }


class device_trace_session:
    """Context manager pairing the host flight recorder with a
    ``jax.profiler`` trace session (real device timelines on TPU; on CPU it
    degrades to a host profile). Correlate the two by step id: every
    ``device_step`` span carries a ``step`` arg, and the XLA executable run
    in the profiler timeline at the same ordinal is that step's device work.

    Usage::

        with device_trace_session("out/device_trace"):
            ... engine traffic ...
        # host spans: engine.export_trace("out/trace_host.json")
        # device timeline: the profiler dump under out/device_trace
    """

    def __init__(self, logdir: str):
        self.logdir = logdir

    def __enter__(self) -> "device_trace_session":
        import jax

        jax.profiler.start_trace(self.logdir)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        import jax

        jax.profiler.stop_trace()
        return False
