"""Drift tracking over per-pane results: the serving-side MetricTracker.

The reference's wrappers layer (``wrappers/tracker.py``, PAPER.md §L5) keeps
a LIST of metric clones — one per epoch — and answers "which step was best".
A serving engine cannot clone itself per window, but the windowed engine
(ISSUE 13, ``engine/windows.py``) produces exactly the stream the tracker
wanted: one result per closed pane. :class:`DriftDetector` consumes that
stream and answers the production question instead: *has this metric
drifted?*

Contracts (mirroring the PR-11 ladder's determinism discipline):

* **Pure in the value sequence.** ``record()`` never reads wall time or
  thread state: the alarm/clear transition sequence is a deterministic
  function of the recorded values alone, so same-seed chaos runs replay the
  identical alarm list (pinned by ``make windows-smoke`` / obs-smoke).
* **Hysteresis-guarded.** A single noisy pane must not page an operator: the
  deviation has to persist ``up_after`` consecutive panes to RAISE and stay
  back inside the band ``down_after`` consecutive panes to CLEAR — the same
  streak vocabulary as :class:`~metrics_tpu.engine.admission.DegradationLadder`.
* **Typed.** Every transition is a :class:`DriftAlarm` record; with
  ``raise_on_alarm=True`` a RAISE transition also raises the typed
  :class:`DriftAlarmError` (standalone use — the engine never enables it on
  the dispatcher thread, where alarms surface as ``drift_alarm`` trace
  events and the ``drift_alarms`` OpenMetrics counter instead).

Standalone usage (no engine needed)::

    det = DriftDetector(threshold=0.1, up_after=2)
    for pane, value in enumerate(pane_results):
        for alarm in det.record(value, pane=pane):
            print(alarm)   # DriftAlarm(kind='raise', name='Accuracy', ...)

Engine wiring: ``EngineConfig(window=..., drift=DriftDetector(...))`` — the
dispatcher evaluates the CLOSING pane's result at every rotation (the
``drift_eval`` fault site; the evaluation is a pure read, so a transient
retries cleanly and the detector records exactly once).
"""
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["DriftAlarm", "DriftAlarmError", "DriftDetector"]

_BASELINES = ("first", "prev", "mean")


@dataclass(frozen=True)
class DriftAlarm:
    """One hysteresis transition of one tracked series.

    ``kind`` is ``"raise"`` (the deviation persisted ``up_after`` panes) or
    ``"clear"`` (back inside the band for ``down_after`` panes). ``key`` is
    the caller's series key (stream id for multi-stream engines, None for a
    single-stream engine); ``name`` the metric name inside a collection
    result ("" for scalar results). ``value``/``baseline``/``delta`` are the
    observation that completed the streak."""

    kind: str
    key: Optional[int]
    name: str
    pane: Optional[int]
    value: float
    baseline: float
    delta: float
    streak: int

    def describe(self) -> str:
        where = f"stream {self.key} " if self.key is not None else ""
        label = f"{self.name} " if self.name else ""
        return (
            f"drift {self.kind}: {where}{label}pane={self.pane} value={self.value:g} "
            f"baseline={self.baseline:g} delta={self.delta:+g} after {self.streak} panes"
        )


class DriftAlarmError(RuntimeError):
    """A raised drift alarm (``raise_on_alarm=True`` standalone mode). Carries
    the typed :class:`DriftAlarm` on ``.alarm``."""

    def __init__(self, alarm: DriftAlarm):
        self.alarm = alarm
        super().__init__(alarm.describe())


@dataclass
class _Series:
    history: List[float] = field(default_factory=list)
    first_value: float = 0.0
    running_sum: float = 0.0   # sum of ALL recorded panes (not history-bounded)
    count: int = 0             # panes recorded so far
    streak_out: int = 0
    streak_in: int = 0
    alarmed: bool = False


class DriftDetector:
    """Hysteresis-guarded drift alarms over a stream of per-pane results.

    Args:
        threshold: absolute deviation from the baseline that counts as "out
            of band" (per series).
        up_after: consecutive out-of-band panes before a RAISE transition.
        down_after: consecutive in-band panes before a CLEAR transition.
        baseline: what the deviation is measured against —

            * ``"first"`` — the series' first recorded pane (a fixed
              reference distribution);
            * ``"prev"`` — the previous pane (rate-of-change drift);
            * ``"mean"`` — the running mean of all panes recorded BEFORE the
              current one (a slowly adapting reference).
        min_panes: panes a series must have recorded before deviations start
            counting (warmup; the baseline needs at least one pane anyway).
        max_history: per-series pane values retained for :meth:`history`
            (oldest dropped; counters and baselines are unaffected — the
            running mean is O(1), not a window over this buffer).
        raise_on_alarm: raise :class:`DriftAlarmError` on RAISE transitions
            (standalone use only — keep False inside an engine).
    """

    def __init__(
        self,
        threshold: float,
        up_after: int = 2,
        down_after: int = 2,
        baseline: str = "first",
        min_panes: int = 1,
        max_history: int = 256,
        raise_on_alarm: bool = False,
    ):
        if threshold <= 0:
            raise ValueError(f"threshold must be positive, got {threshold}")
        if up_after < 1 or down_after < 1:
            raise ValueError(
                f"up_after/down_after must be >= 1, got {up_after}/{down_after}"
            )
        if baseline not in _BASELINES:
            raise ValueError(f"baseline must be one of {_BASELINES}, got {baseline!r}")
        if min_panes < 1:
            raise ValueError(f"min_panes must be >= 1, got {min_panes}")
        if max_history < 1:
            raise ValueError(f"max_history must be >= 1, got {max_history}")
        self.threshold = float(threshold)
        self.up_after = int(up_after)
        self.down_after = int(down_after)
        self.baseline = baseline
        self.min_panes = int(min_panes)
        self.max_history = int(max_history)
        self.raise_on_alarm = bool(raise_on_alarm)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[Optional[int], str], _Series] = {}
        self._alarms: List[DriftAlarm] = []
        self.evals = 0

    # ------------------------------------------------------------------ recording

    @staticmethod
    def _flatten(value: Any) -> Dict[str, float]:
        """One pane result -> named scalar series. Collections record one
        series per member; scalar results record the anonymous series ``""``.
        Non-scalar (curve/array) members are skipped — drift over a curve
        needs a scalar projection the caller owns."""
        import numpy as np

        if isinstance(value, dict):
            out: Dict[str, float] = {}
            for k, v in value.items():
                arr = np.asarray(v)
                if arr.ndim == 0:
                    out[str(k)] = float(arr)
            return out
        arr = np.asarray(value)
        return {"": float(arr)} if arr.ndim == 0 else {}

    def record(
        self, value: Any, key: Optional[int] = None, pane: Optional[int] = None
    ) -> List[DriftAlarm]:
        """Record one closed pane's result for series ``key``; returns the
        hysteresis transitions (possibly empty) this pane completed, in
        series order. Deterministic in the value sequence; thread-safe."""
        transitions: List[DriftAlarm] = []
        flat = self._flatten(value)
        with self._lock:
            self.evals += 1
            for name, v in flat.items():
                s = self._series.setdefault((key, name), _Series())
                base: Optional[float] = None
                if s.count >= 1:
                    if self.baseline == "first":
                        base = s.first_value
                    elif self.baseline == "prev":
                        base = s.history[-1]
                    else:  # running mean of every pane BEFORE this one, O(1)
                        base = s.running_sum / s.count
                transitions.extend(
                    self._advance(s, key, name, pane, v, base, s.count)
                )
                # commit the observation AFTER the verdict (a pane judges
                # against the baseline that preceded it)
                if s.count == 0:
                    s.first_value = v
                s.running_sum += v
                s.count += 1
                s.history.append(v)
                if len(s.history) > self.max_history:
                    del s.history[0]
            self._alarms.extend(transitions)
        if self.raise_on_alarm:
            for a in transitions:
                if a.kind == "raise":
                    raise DriftAlarmError(a)
        return transitions

    def _advance(
        self,
        s: _Series,
        key: Optional[int],
        name: str,
        pane: Optional[int],
        v: float,
        base: Optional[float],
        n_prev: int,
    ) -> List[DriftAlarm]:
        """One hysteresis step for one series (lock held)."""
        if base is None or n_prev < self.min_panes:
            return []
        delta = v - base
        out: List[DriftAlarm] = []
        if abs(delta) > self.threshold:
            s.streak_out += 1
            s.streak_in = 0
            if not s.alarmed and s.streak_out >= self.up_after:
                s.alarmed = True
                out.append(DriftAlarm(
                    kind="raise", key=key, name=name, pane=pane,
                    value=v, baseline=base, delta=delta, streak=s.streak_out,
                ))
        else:
            s.streak_in += 1
            s.streak_out = 0
            if s.alarmed and s.streak_in >= self.down_after:
                s.alarmed = False
                out.append(DriftAlarm(
                    kind="clear", key=key, name=name, pane=pane,
                    value=v, baseline=base, delta=delta, streak=s.streak_in,
                ))
        return out

    # -------------------------------------------------------------------- reading

    def alarms(self, kind: Optional[str] = None) -> List[DriftAlarm]:
        with self._lock:
            return [a for a in self._alarms if kind is None or a.kind == kind]

    def alarmed_series(self) -> List[Tuple[Optional[int], str]]:
        """Series currently in the alarmed state (the gauge surface)."""
        with self._lock:
            return sorted(
                (k for k, s in self._series.items() if s.alarmed),
                key=lambda kn: (kn[0] is not None, kn[0] if kn[0] is not None else 0, kn[1]),
            )

    def history(self, key: Optional[int] = None, name: str = "") -> List[float]:
        """The retained per-pane values of one series (the MetricTracker
        ``compute_all`` analogue, bounded by ``max_history``)."""
        with self._lock:
            s = self._series.get((key, name))
            return list(s.history) if s is not None else []

    def summary(self) -> Dict[str, Any]:
        """The drift block engine telemetry embeds (deterministic ordering)."""
        with self._lock:
            return {
                "evals": self.evals,
                "series": len(self._series),
                "alarms_raised": sum(1 for a in self._alarms if a.kind == "raise"),
                "alarms_cleared": sum(1 for a in self._alarms if a.kind == "clear"),
                "alarmed": [
                    {"key": k, "name": n}
                    for k, n in sorted(
                        (kn for kn, s in self._series.items() if s.alarmed),
                        key=lambda kn: (
                            kn[0] is not None, kn[0] if kn[0] is not None else 0, kn[1]
                        ),
                    )
                ],
                "threshold": self.threshold,
                "baseline": self.baseline,
            }

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self._alarms.clear()
            self.evals = 0
