"""Host-side stream paging: an LRU pager over the stream-sharded arena.

The stream-sharded :class:`~metrics_tpu.engine.multistream.MultiStreamEngine`
(ISSUE 9) bounds device memory by the ACTIVE WORKING SET, not the tenant
count: each shard's arena carries ``resident`` slots of per-stream state, and
streams beyond that live in host RAM as spilled per-dtype row vectors — the
same numpy form the snapshot codec serializes (``engine/snapshot.py``
numpy-ifies exactly these arrays into the payload), so a snapshot covers
spilled rows for free and kill/resume replay is exact through a spill.

This module is BOOKKEEPING ONLY: slot tables, LRU order, and the host-RAM
spill store. All device I/O (reading a row out of the arena to spill it,
scattering a faulted-in row back) stays in the engine, which batches it per
routed group — the pager just answers "which slot, and what must move".
Determinism matters (chaos runs replay): every decision here is a pure
function of the submit order, never of wall time.

Capacity invariant: a single routed step may touch at most ``resident``
distinct streams per shard (the engine's round builder enforces it), so
:meth:`plan_residency` can always seat a round — evicting only streams the
round does not need.
"""
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PageOp", "StreamPager"]


class PageOp:
    """One planned residency change on one shard.

    ``kind`` is ``"evict"`` (slot's current stream spills to host RAM) or
    ``"load"`` (``stream`` faults into ``slot`` — from its spilled row when
    one exists, else from the metric's init row). The engine executes evicts
    before loads, batched per dtype.
    """

    __slots__ = ("kind", "shard", "slot", "stream")

    def __init__(self, kind: str, shard: int, slot: int, stream: int):
        self.kind = kind
        self.shard = shard
        self.slot = slot
        self.stream = stream

    def __repr__(self) -> str:  # debugging/chaos-log aid
        return f"PageOp({self.kind}, shard={self.shard}, slot={self.slot}, stream={self.stream})"


class StreamPager:
    """Slot tables + LRU order + host-RAM spill store for ``world`` shards.

    Streams are identified by their LOCAL index on their home shard
    (``global_sid // world``); the engine owns the global→(shard, local)
    routing rule. ``resident`` is the per-shard slot count.
    """

    def __init__(self, world: int, resident: int):
        if world <= 0 or resident <= 0:
            raise ValueError(f"world and resident must be positive, got {world}, {resident}")
        self.world = int(world)
        self.resident = int(resident)
        # per shard: slot j -> local stream (or None when free)
        self._slots: List[List[Optional[int]]] = [
            [None] * self.resident for _ in range(self.world)
        ]
        # per shard: local stream -> slot, in LRU order (oldest first)
        self._lru: List["OrderedDict[int, int]"] = [OrderedDict() for _ in range(self.world)]
        # per shard: local stream -> spilled per-dtype row vectors (host numpy)
        self._spill: List[Dict[int, Dict[str, np.ndarray]]] = [
            {} for _ in range(self.world)
        ]
        # running byte total of the spill store, maintained incrementally at
        # the points rows enter/leave (commit/drop/reset/load_payload) — a
        # recount per gauge refresh would be O(spilled x dtypes) Python work
        # on every paging round, worst exactly when paging pressure is highest
        self._spill_bytes = 0

    # ------------------------------------------------------------------ queries

    def slot_of(self, shard: int, stream: int) -> Optional[int]:
        return self._lru[shard].get(stream)

    def spilled_row(self, shard: int, stream: int) -> Optional[Dict[str, np.ndarray]]:
        return self._spill[shard].get(stream)

    def resident_count(self) -> int:
        return sum(len(l) for l in self._lru)

    def spilled_count(self) -> int:
        return sum(len(s) for s in self._spill)

    def spill_nbytes(self) -> int:
        """Host-RAM bytes the spill store currently holds — the observable
        ``compress_payloads`` shrinks (rows arrive here already encoded by
        the engine's at-rest codec; the pager stores whatever per-dtype
        vectors it is handed, compressed or verbatim). O(1): maintained
        incrementally where rows enter and leave the store."""
        return self._spill_bytes

    @staticmethod
    def _row_nbytes(row: Optional[Dict[str, np.ndarray]]) -> int:
        return sum(int(v.nbytes) for v in row.values()) if row else 0

    def tenancy_stats(self) -> Dict[str, int]:
        """The pager's contribution to the fleet tenancy gauges (ISSUE 20):
        resident/spilled row counts and the spill store's host-RAM bytes —
        one O(world) scrape the OpenMetrics exposition and ``engine_report``
        read per refresh, so per-host device residency can be asserted FLAT
        while the stream universe grows."""
        return {
            "resident_rows": self.resident_count(),
            "spilled_rows": self.spilled_count(),
            "spill_bytes": self.spill_nbytes(),
            "capacity_rows": self.world * self.resident,
        }

    def resident_streams(self, shard: int) -> Tuple[int, ...]:
        return tuple(self._lru[shard])

    def spilled_streams(self, shard: int) -> Tuple[int, ...]:
        """Local stream coordinates currently living in the host spill store
        (sorted — deterministic enumeration for the windowed rotation's
        pane-expiry plan)."""
        return tuple(sorted(self._spill[shard]))

    # ----------------------------------------------------------------- planning

    def plan_residency(self, shard: int, streams: List[int]) -> Tuple[List[PageOp], int, int]:
        """Plan (without executing) the page ops seating ``streams`` on
        ``shard``; returns ``(ops, hits, faults)``. Raises when the distinct
        set exceeds the shard's slot count — the round builder's invariant.
        Does NOT mutate tables: the engine executes the device I/O first and
        then calls :meth:`commit`, so an injected page fault retried mid-plan
        can never leave the bookkeeping ahead of the buffers."""
        need = list(dict.fromkeys(int(s) for s in streams))  # ordered distinct
        if len(need) > self.resident:
            raise ValueError(
                f"round touches {len(need)} distinct streams on shard {shard}, "
                f"but only {self.resident} slots are resident"
            )
        lru = self._lru[shard]
        slots = self._slots[shard]
        hits = sum(1 for s in need if s in lru)
        missing = [s for s in need if s not in lru]
        ops: List[PageOp] = []
        if missing:
            free = [j for j, occupant in enumerate(slots) if occupant is None]
            needed_set = set(need)
            # evict oldest residents the round does not need, one per missing
            # stream beyond the free slots
            evictable = (s for s in lru if s not in needed_set)
            for s in need:
                if s in lru:
                    continue
                if free:
                    slot = free.pop(0)
                else:
                    victim = next(evictable)
                    slot = lru[victim]
                    ops.append(PageOp("evict", shard, slot, victim))
                ops.append(PageOp("load", shard, slot, s))
        return ops, hits, len(missing)

    def commit(self, ops: List[PageOp], spilled_rows: Dict[Tuple[int, int], Dict[str, np.ndarray]]) -> None:
        """Apply planned ops to the tables after the engine moved the bytes.
        ``spilled_rows`` maps ``(shard, stream)`` of each evict to the row
        vectors read out of the arena (stored in the host spill store); each
        load's stream drops its spill entry (the row is resident again)."""
        for op in ops:
            lru = self._lru[op.shard]
            slots = self._slots[op.shard]
            if op.kind == "evict":
                row = spilled_rows[(op.shard, op.stream)]
                self._spill_bytes += self._row_nbytes(row) - self._row_nbytes(
                    self._spill[op.shard].get(op.stream)
                )
                self._spill[op.shard][op.stream] = row
                lru.pop(op.stream, None)
                slots[op.slot] = None
            else:
                self._spill_bytes -= self._row_nbytes(
                    self._spill[op.shard].pop(op.stream, None)
                )
                slots[op.slot] = op.stream
                lru[op.stream] = op.slot

    def touch(self, shard: int, streams: List[int]) -> None:
        """Refresh LRU recency for the streams a routed step just updated
        (submit order = recency order, deterministically)."""
        lru = self._lru[shard]
        for s in dict.fromkeys(int(x) for x in streams):
            if s in lru:
                lru.move_to_end(s)

    def drop(self, shard: int, stream: int) -> Optional[int]:
        """Forget a stream entirely (``reset_stream``): its spill entry is
        discarded and its slot freed — the next access faults in the metric's
        init row. Returns the freed slot (None when it was not resident)."""
        self._spill_bytes -= self._row_nbytes(self._spill[shard].pop(stream, None))
        slot = self._lru[shard].pop(stream, None)
        if slot is not None:
            self._slots[shard][slot] = None
        return slot

    def reset(self) -> None:
        for shard in range(self.world):
            self._slots[shard] = [None] * self.resident
            self._lru[shard].clear()
            self._spill[shard].clear()
        self._spill_bytes = 0

    # ----------------------------------------------------- snapshot round-trip

    def snapshot_payload(self) -> Dict[str, Any]:
        """The pager's durable form, snapshot-codec-ready (numpy only): the
        ``(world, resident)`` slot table (-1 = free) and the spilled rows as
        one ``(K, n_dtype)`` matrix per dtype plus their ``(K, 2)``
        (shard, stream) coordinates — exact replay through a spill needs
        every one of these."""
        slot_table = np.full((self.world, self.resident), -1, np.int64)
        for w in range(self.world):
            for j, s in enumerate(self._slots[w]):
                if s is not None:
                    slot_table[w, j] = s
        coords: List[Tuple[int, int]] = []
        for w in range(self.world):
            for s in sorted(self._spill[w]):
                coords.append((w, s))
        payload: Dict[str, Any] = {"slots": slot_table}
        # the spill block is OMITTED when empty: zero-size arrays break the
        # orbax ocdbt save path, and an absent key round-trips cleanly
        if coords:
            payload["spill_coords"] = np.asarray(coords, np.int64).reshape(len(coords), 2)
            dtypes = sorted(self._spill[coords[0][0]][coords[0][1]])
            for key in dtypes:
                payload[f"spill_{key}"] = np.stack(
                    [self._spill[w][s][key] for w, s in coords]
                )
        return payload

    def load_payload(self, payload: Dict[str, Any]) -> None:
        """Inverse of :meth:`snapshot_payload` (same world/resident only)."""
        slot_table = np.asarray(payload["slots"])
        if slot_table.shape != (self.world, self.resident):
            raise ValueError(
                f"pager payload is {slot_table.shape}, this pager is "
                f"({self.world}, {self.resident})"
            )
        self.reset()
        for w in range(self.world):
            for j in range(self.resident):
                s = int(slot_table[w, j])
                if s >= 0:
                    self._slots[w][j] = s
                    self._lru[w][s] = j
        coords = np.asarray(payload.get("spill_coords", np.zeros((0, 2), np.int64))).reshape(-1, 2)
        spill_keys = [k[len("spill_"):] for k in payload if k.startswith("spill_") and k != "spill_coords"]
        for i, (w, s) in enumerate(coords):
            row = {key: np.asarray(payload[f"spill_{key}"][i]) for key in spill_keys}
            self._spill[int(w)][int(s)] = row
            self._spill_bytes += self._row_nbytes(row)
