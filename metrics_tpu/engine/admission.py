"""SLO-aware admission control + the graceful-degradation ladder (ISSUE 11).

Overload is the failure mode the fault layer (``engine/faults.py``) cannot
model: nothing is *broken*, there is simply more traffic than the topology can
fold, and an engine without admission control converts that into unbounded
queue growth and tail-latency collapse. This module supplies the three
self-defense pieces ``engine/pipeline.py`` wires in:

* :class:`AdmissionPolicy` — per-stream token buckets with priority classes.
  Every ``submit`` either consumes ``rows`` tokens from the stream's bucket or
  raises the typed :class:`AdmissionRejected` carrying ``retry_after_s`` (the
  bucket's own refill arithmetic — producers get an honest backoff hint, not a
  blind retry loop). Rides the screen/quarantine vocabulary: an admission
  rejection is a REFUSED batch, never a folded-then-discarded one, so the
  replay-cursor and exactness contracts are untouched. The SHED switch
  (:meth:`AdmissionPolicy.shed_lowest`) rejects the lowest priority class
  outright — the ladder's last rung.
* :class:`OverloadDetector` — the sustained-overload test, fed by recorder
  spans / engine telemetry: p99 queue residency (the ``queue_wait_us``
  histogram when the flight recorder is on, the stats ring otherwise), the
  pager spill rate, and queue fill. Value-level hysteresis: overload asserts
  when ANY armed high-watermark is crossed, and clears only when EVERY signal
  is back under its (lower) clear-watermark.
* :class:`DegradationLadder` — the deterministic, hysteresis-guarded policy
  that walks a fixed rung sequence under sustained overload (default: widen
  ``coalesce_window_ms`` → force ``sync_precision`` quantization for eligible
  states → defer cold-stream ``result()`` reads → shed the lowest priority
  class) and walks back down when the detector clears. ``tick()`` is a PURE
  function of the detector verdict sequence — no wall time, no randomness —
  so a scripted signal sequence replays to the identical transition list, and
  every engine-side transition is emitted as a trace event
  (``docs/observability.md``).

Zero cost when disabled (the PR 6/PR 8 contract): no ``AdmissionPolicy`` and
no ``DegradationLadder`` on the config means the hot path pays one
``is not None`` check per site and never enters this module — asserted by the
``obs_overhead`` bench's structural guard, which profiles this file alongside
``trace.py``.

Like ``faults.py``, deliberately dependency-free within the engine package.
"""
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "AdmissionPolicy",
    "AdmissionRejected",
    "DegradationLadder",
    "LADDER_RUNGS",
    "OverloadDetector",
    "TokenBucket",
]

# The full rung sequence, in escalation order. A ladder may run any ordered
# subset — rungs it omits are simply never engaged.
LADDER_RUNGS = ("widen_coalesce", "quantize_sync", "defer_cold_reads", "shed")


class AdmissionRejected(RuntimeError):
    """A submit refused by the admission policy (typed, producer-facing).

    ``retry_after_s`` is the bucket's own estimate of when ``rows`` tokens
    will exist again (``float("inf")`` for a SHED stream — its class is
    rejected outright until the ladder de-escalates, so there is no useful
    backoff). ``shed`` distinguishes the two: a rate rejection is transient
    backpressure, a shed rejection is the engine deliberately dropping the
    lowest priority class to protect the rest.
    """

    def __init__(
        self,
        reason: str,
        retry_after_s: float,
        stream_id: Optional[int] = None,
        priority: int = 0,
        shed: bool = False,
    ):
        self.retry_after_s = float(retry_after_s)
        self.stream_id = stream_id
        self.priority = int(priority)
        self.shed = bool(shed)
        where = "engine" if stream_id is None else f"stream {stream_id}"
        hint = (
            "shed until the degradation ladder de-escalates"
            if shed
            else f"retry_after_s={self.retry_after_s:.4f}"
        )
        super().__init__(
            f"admission rejected for {where} (priority {self.priority}): {reason} ({hint})"
        )


class TokenBucket:
    """One stream's token bucket: ``capacity`` tokens, refilled at ``rate``
    tokens/second of the policy's clock. NOT thread-safe on its own — the
    owning :class:`AdmissionPolicy` serializes access under one lock."""

    __slots__ = ("capacity", "rate", "tokens", "stamp")

    def __init__(self, capacity: float, rate: float, now: float):
        self.capacity = float(capacity)
        self.rate = float(rate)
        self.tokens = float(capacity)
        self.stamp = float(now)

    def take(self, n: float, now: float) -> float:
        """Consume ``n`` tokens; returns 0.0 on success, else the seconds
        until ``n`` tokens will exist (nothing consumed)."""
        if now > self.stamp:
            self.tokens = min(self.capacity, self.tokens + (now - self.stamp) * self.rate)
        self.stamp = max(self.stamp, now)
        if self.tokens >= n:
            self.tokens -= n
            return 0.0
        if n > self.capacity or self.rate <= 0:
            # the bucket can NEVER hold n tokens: honest inf, not a backoff
            return float("inf")
        return (n - self.tokens) / self.rate


class AdmissionPolicy:
    """Per-stream token buckets with priority classes + the shed switch.

    Args:
        rows_per_s: refill rate of each stream's bucket, in rows/second
            (scaled per priority class by ``class_rates``).
        burst_rows: bucket capacity — the largest burst one stream may land
            instantly. Size it >= the biggest single batch a producer
            submits: a batch larger than the capacity can never be admitted
            and is refused with ``retry_after_s=inf`` (the bucket can never
            hold that many tokens — an honest "resize your batches" signal,
            not a backoff hint).
        priorities: ``{stream_id: priority_class}`` (0 = highest). Streams
            not named get ``default_priority``. The base (single-stream)
            engine admits under ``stream_id=None``, one bucket, class
            ``default_priority``.
        default_priority: class for unnamed streams.
        class_rates: per-class multiplier on ``rows_per_s`` (absent = 1.0) —
            how a priority class buys more or less sustained throughput.
        clock: the time source (seconds, monotonic). Defaults to
            ``time.monotonic``; tests and deterministic harnesses inject a
            logical clock.

    Thread-safe: producers submit concurrently, and the admitted/rejected/
    shed counters must not lose increments (a plain ``+= 1`` is a
    read-modify-write the GIL does not make atomic) — every bucket op and
    counter bump happens under one lock, tested under concurrent submits in
    ``tests/engine/test_admission.py``.
    """

    def __init__(
        self,
        rows_per_s: float = 1e9,
        burst_rows: float = 1e9,
        priorities: Optional[Dict[int, int]] = None,
        default_priority: int = 1,
        class_rates: Optional[Dict[int, float]] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        if rows_per_s <= 0 or burst_rows <= 0:
            raise ValueError(
                f"rows_per_s and burst_rows must be positive, got {rows_per_s}, {burst_rows}"
            )
        self.rows_per_s = float(rows_per_s)
        self.burst_rows = float(burst_rows)
        self.priorities = dict(priorities or {})
        self.default_priority = int(default_priority)
        self.class_rates = dict(class_rates or {})
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._buckets: Dict[Any, TokenBucket] = {}
        self._shed_floor: Optional[int] = None  # classes >= floor are shed
        # lifetime outcome counters by priority class — the stats block's
        # admission source of truth (engine copies them at render time)
        self._admitted: Dict[int, int] = {}
        self._rejected: Dict[int, int] = {}
        self._shed: Dict[int, int] = {}

    # --------------------------------------------------------------- priority

    def priority_of(self, stream_id: Optional[int]) -> int:
        if stream_id is None:
            return self.default_priority
        return self.priorities.get(int(stream_id), self.default_priority)

    def lowest_priority(self) -> int:
        """The numerically largest (= least important) class in play."""
        return max([self.default_priority, *self.priorities.values()])

    # ------------------------------------------------------------------- shed

    def shed_lowest(self, on: bool) -> None:
        """Engage/release the ladder's shed rung: reject the lowest priority
        class outright. Idempotent; releasing restores normal admission."""
        with self._lock:
            self._shed_floor = self.lowest_priority() if on else None

    def shed_floor(self) -> Optional[int]:
        with self._lock:
            return self._shed_floor

    def is_shed(self, stream_id: Optional[int]) -> bool:
        with self._lock:
            return self._shed_floor is not None and (
                self.priority_of(stream_id) >= self._shed_floor
            )

    # ------------------------------------------------------------------ admit

    def admit(self, stream_id: Optional[int], rows: int) -> int:
        """Admit ``rows`` for ``stream_id`` or raise :class:`AdmissionRejected`.

        Returns the stream's priority class on success (for telemetry).
        Shed classes reject before touching a bucket; a rate rejection
        consumes nothing and carries the bucket's refill estimate.
        """
        prio = self.priority_of(stream_id)
        with self._lock:
            if self._shed_floor is not None and prio >= self._shed_floor:
                self._shed[prio] = self._shed.get(prio, 0) + 1
                raise AdmissionRejected(
                    f"priority class {prio} is shed under the degradation ladder",
                    retry_after_s=float("inf"),
                    stream_id=stream_id,
                    priority=prio,
                    shed=True,
                )
            now = self._clock()
            bucket = self._buckets.get(stream_id)
            if bucket is None:
                rate = self.rows_per_s * float(self.class_rates.get(prio, 1.0))
                bucket = self._buckets[stream_id] = TokenBucket(self.burst_rows, rate, now)
            wait = bucket.take(float(max(0, rows)), now)
            if wait > 0.0:
                self._rejected[prio] = self._rejected.get(prio, 0) + 1
                raise AdmissionRejected(
                    f"token bucket empty ({rows} rows over rate)",
                    retry_after_s=wait,
                    stream_id=stream_id,
                    priority=prio,
                )
            self._admitted[prio] = self._admitted.get(prio, 0) + 1
            return prio

    def refund(self, stream_id: Optional[int], rows: int, priority: Optional[int] = None) -> None:
        """Return tokens consumed by an :meth:`admit` whose batch never
        entered the engine (the enqueue was refused — a full queue's
        ``BackpressureTimeout``, or a sticky dispatcher raise): credits the
        bucket back up to capacity and reverses the admitted count, so a
        timing-out producer is not double-charged exactly when tokens are
        scarcest."""
        prio = self.priority_of(stream_id) if priority is None else int(priority)
        with self._lock:
            bucket = self._buckets.get(stream_id)
            if bucket is not None:
                bucket.tokens = min(bucket.capacity, bucket.tokens + float(max(0, rows)))
            if self._admitted.get(prio, 0) > 0:
                self._admitted[prio] -= 1

    def counters(self) -> Dict[str, Dict[int, int]]:
        """One consistent snapshot of the outcome counters, by priority."""
        with self._lock:
            return {
                "admitted": dict(self._admitted),
                "rejected": dict(self._rejected),
                "shed": dict(self._shed),
            }


class OverloadDetector:
    """The sustained-overload test the ladder consults once per dispatcher
    group. Signals come from recorder spans and engine telemetry (the engine
    assembles them — ``queue_p99_us`` from the flight recorder's
    ``queue_wait_us`` histogram when one is attached, the stats ring
    otherwise; ``spill_rate`` = pager spill-outs per routed step over the
    tick window; ``queue_depth_frac`` = ingest-queue fill).

    Value hysteresis: :meth:`assess` flips to overloaded when ANY armed high
    watermark is crossed, and back only when EVERY signal is under its clear
    watermark (default = ``clear_frac`` x high). A None threshold disarms
    that signal. Count hysteresis (how many consecutive verdicts move the
    ladder) lives in :class:`DegradationLadder`.
    """

    def __init__(
        self,
        queue_p99_us: Optional[float] = 50_000.0,
        spill_rate: Optional[float] = None,
        queue_depth_frac: Optional[float] = 0.9,
        clear_frac: float = 0.5,
    ):
        if not (0.0 < clear_frac <= 1.0):
            raise ValueError(f"clear_frac must be in (0, 1], got {clear_frac}")
        self.queue_p99_us = queue_p99_us
        self.spill_rate = spill_rate
        self.queue_depth_frac = queue_depth_frac
        self.clear_frac = float(clear_frac)
        self._overloaded = False

    def _checks(self, signals: Dict[str, float]) -> List[Tuple[float, float]]:
        out: List[Tuple[float, float]] = []
        for key, high in (
            ("queue_p99_us", self.queue_p99_us),
            ("spill_rate", self.spill_rate),
            ("queue_depth_frac", self.queue_depth_frac),
        ):
            if high is not None:
                out.append((float(signals.get(key, 0.0) or 0.0), float(high)))
        return out

    def assess(self, signals: Dict[str, float]) -> bool:
        """The hysteresis-guarded verdict for one tick's signals."""
        checks = self._checks(signals)
        if not checks:
            return False
        if any(v >= high for v, high in checks):
            self._overloaded = True
        elif all(v < high * self.clear_frac for v, high in checks):
            self._overloaded = False
        return self._overloaded

    def reset(self) -> None:
        self._overloaded = False


class DegradationLadder:
    """The deterministic overload→degradation policy.

    ``rungs`` is an ordered subset of :data:`LADDER_RUNGS`; level 0 = healthy,
    level k = rungs[:k] engaged. One :meth:`tick` per dispatcher group:
    ``up_after`` consecutive overloaded verdicts escalate ONE rung,
    ``down_after`` consecutive healthy verdicts release one — streaks reset
    on any opposite verdict and after each transition, so a flapping signal
    cannot oscillate the engine (count hysteresis on top of the detector's
    value hysteresis). Pure in the verdict sequence: no wall time, no
    randomness — a scripted signal sequence replays to the identical
    transition list (pinned in ``tests/engine/test_admission.py``), which is
    what lets same-seed serving runs emit identical ladder trace events.

    ``widen_window_ms`` parameterizes the first rung (what the engine sets
    ``coalesce_window_ms`` to while engaged).
    """

    def __init__(
        self,
        detector: Optional[OverloadDetector] = None,
        rungs: Tuple[str, ...] = LADDER_RUNGS,
        up_after: int = 2,
        down_after: int = 4,
        widen_window_ms: float = 5.0,
    ):
        unknown = [r for r in rungs if r not in LADDER_RUNGS]
        if unknown:
            raise ValueError(f"unknown ladder rungs {unknown}; expected from {LADDER_RUNGS}")
        order = {r: i for i, r in enumerate(LADDER_RUNGS)}
        if list(rungs) != sorted(rungs, key=order.__getitem__) or len(set(rungs)) != len(rungs):
            raise ValueError(
                f"rungs must be an ordered subset of {LADDER_RUNGS}, got {rungs}"
            )
        if up_after <= 0 or down_after <= 0:
            raise ValueError("up_after and down_after must be positive")
        self.detector = detector if detector is not None else OverloadDetector()
        self.rungs = tuple(rungs)
        self.up_after = int(up_after)
        self.down_after = int(down_after)
        self.widen_window_ms = float(widen_window_ms)
        self.level = 0
        self._hot = 0
        self._cool = 0

    def rung(self, level: int) -> str:
        """The rung engaged by moving from ``level - 1`` to ``level``."""
        return self.rungs[level - 1]

    def tick(self, signals: Dict[str, float]) -> Optional[Tuple[int, int]]:
        """One evaluation; returns ``(from_level, to_level)`` on a transition,
        None otherwise. At most one rung moves per tick."""
        if self.detector.assess(signals):
            self._hot += 1
            self._cool = 0
            if self._hot >= self.up_after and self.level < len(self.rungs):
                self._hot = 0
                self.level += 1
                return (self.level - 1, self.level)
        else:
            self._cool += 1
            self._hot = 0
            if self._cool >= self.down_after and self.level > 0:
                self._cool = 0
                self.level -= 1
                return (self.level + 1, self.level)
        return None

    def reset(self) -> None:
        self.level = 0
        self._hot = 0
        self._cool = 0
        self.detector.reset()
