"""Embedded-model serving smoke: ``python -m metrics_tpu.engine.model_smoke``.

The CPU-safe gate for the ISSUE 19 model-host stack (``make model-smoke``),
on the bootstrap 8-device virtual mesh:

1. sharded-vs-single parity — the hybrid Inception layout (tensor-parallel
   128-lane stem + data-parallel trunk, ``all_gather``-only) serves features
   matching the single-device host within float tolerance, and the
   pipeline-staged encoder (``ppermute``-only GPipe handoff) is BIT-exact vs
   the sequential stage fold; the single-device f32 host is BIT-exact vs the
   direct module forward at the bucket shape;
2. shared-host dedupe — ``FID`` and ``KID`` built over the same (tap, params
   fingerprint, precision, buckets) resolve to ONE resident host
   (``shared_by == 2``) whose param buffers are the same objects;
3. zero steady compiles — replaying the same traffic mix over a warmed host
   compiles NOTHING (the ``AotCache`` miss counter is the observable, same
   contract as every engine gate);
4. collective allowance — the ``host-collectives-pinned`` rule audits every
   compiled host program clean (hybrid may only ``all_gather``, pipeline may
   only ``ppermute``), and the OpenMetrics ``model_host_*`` families parse
   through the strict parser with the activation-precision label;
5. kill/resume with a host attached — a snapshotting engine fed by a host is
   killed after a snapshot boundary, a FRESH engine (fresh host) restores
   and replays the remainder: the result is bit-identical to the
   uninterrupted run.

Prints one PASS line; exits nonzero on any violated claim. Optional argv:
an output path for the host telemetry JSON (``out/model_telemetry.json``).
"""
import os
import subprocess
import sys

NUM_DEVICES = 8
INPUT_SIZE = 75  # smallest viable InceptionV3 input: CPU-cheap compiles


def _bootstrap() -> int:
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={NUM_DEVICES}"
    )
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import sys; from metrics_tpu.engine.model_smoke import _impl; "
        "sys.exit(_impl(sys.argv[1] if len(sys.argv) > 1 else None))"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code] + sys.argv[1:], env=env, timeout=900
    )
    return proc.returncode


def _impl(out_path=None) -> int:
    import json
    import tempfile

    import numpy as np

    import jax
    from jax.sharding import Mesh

    from metrics_tpu import MeanSquaredError
    from metrics_tpu.analysis.rules import check_host_collectives_pinned
    from metrics_tpu.engine import (
        EngineConfig,
        ModelHostConfig,
        StreamingEngine,
        encoder_host,
        inception_host,
        reset_host_registry,
    )
    from metrics_tpu.models.inception import random_inception_params

    devs = jax.devices()
    if len(devs) < NUM_DEVICES:
        print(f"FAIL: need {NUM_DEVICES} devices, have {len(devs)}")
        return 1
    mesh = Mesh(np.asarray(devs[:NUM_DEVICES]), ("dp",))
    ok = True
    telemetry = {}
    reset_host_registry()

    rng = np.random.RandomState(0)
    params = random_inception_params(input_size=INPUT_SIZE, seed=0, fast=True)
    img_batches = [
        rng.randint(0, 255, size=(n, INPUT_SIZE, INPUT_SIZE, 3)).astype(np.uint8)
        for n in (5, 8, 3, 6)
    ]

    # ---- 1a. single-device f32 host is BIT-exact vs the direct module forward
    import jax.numpy as jnp

    from metrics_tpu.models.inception import InceptionV3

    single = inception_host(
        "2048", params, config=ModelHostConfig(buckets=(8,), coalesce_window_ms=0.0),
        shared=False,
    )
    module = InceptionV3()
    direct = jax.jit(lambda p, x: module.apply(p, x)["2048"])
    single_feats, direct_feats = [], []
    for imgs in img_batches:
        single_feats.append(np.asarray(single.infer(imgs)))
        # the bit-exactness contract holds at the SAME padded (bucket) shape:
        # conv rows are independent, so valid rows of the padded program match
        pad = np.zeros((8,) + imgs.shape[1:], imgs.dtype)
        pad[: imgs.shape[0]] = imgs
        direct_feats.append(
            np.asarray(direct(params, jnp.asarray(pad)))[: imgs.shape[0]].astype(np.float32)
        )
    if not all(np.array_equal(a, b) for a, b in zip(single_feats, direct_feats)):
        print("FAIL: single-device f32 host features not bit-identical to the module forward")
        ok = False

    # ---- 1b. hybrid stem-tensor layout on the 8-device mesh: float parity
    hybrid = inception_host(
        "2048", params,
        config=ModelHostConfig(buckets=(8,), mesh=mesh, coalesce_window_ms=0.0),
        shared=False,
    )
    for imgs, want in zip(img_batches, single_feats):
        got = np.asarray(hybrid.infer(imgs))
        if not np.allclose(got, want, rtol=1e-4, atol=1e-5):
            print(
                "FAIL: hybrid sharded features diverge from single-device: "
                f"max abs diff {float(np.abs(got - want).max()):.3e}"
            )
            ok = False
            break

    # ---- 1c. pipeline-staged encoder: BIT-exact vs the sequential stage fold
    dim = 16
    stage_w = rng.randn(NUM_DEVICES, dim, dim).astype(np.float32) * 0.3

    def stage_fn(w, x):
        return jnp.tanh(x @ w)

    pipe = encoder_host(
        stage_fn=stage_fn, stage_params=stage_w,
        config=ModelHostConfig(buckets=(8,), mesh=mesh, coalesce_window_ms=0.0),
        fingerprint="model-smoke-pipeline", shared=False,
    )
    ids = rng.randn(13, dim).astype(np.float32)
    got = np.asarray(pipe.infer(ids, np.ones_like(ids)))
    want = ids
    for s in range(NUM_DEVICES):
        want = np.asarray(jax.jit(stage_fn)(stage_w[s], jnp.asarray(want)))
    if not np.array_equal(got, want):
        print(
            "FAIL: pipeline encoder not bit-exact vs sequential stages: "
            f"max abs diff {float(np.abs(got - want).max()):.3e}"
        )
        ok = False

    # ---- 2. shared-host dedupe: FID + KID over the same weights -> ONE model
    from metrics_tpu.image.fid import FID
    from metrics_tpu.image.kid import KID

    shared_cfg = ModelHostConfig(buckets=(8,), coalesce_window_ms=0.0)
    fid = FID(feature=2048, params=params, model_host=shared_cfg)
    kid = KID(feature=2048, params=params, subsets=2, subset_size=4, model_host=shared_cfg)
    if fid.model_host is not kid.model_host:
        print("FAIL: FID and KID over the same weights built TWO hosts")
        ok = False
    elif fid.model_host.counters()["shared_by"] != 2:
        print(f"FAIL: shared_by = {fid.model_host.counters()['shared_by']}, expected 2")
        ok = False
    leaves_a = jax.tree.leaves(fid.model_host.params)
    leaves_b = jax.tree.leaves(kid.model_host.params)
    if not all(a is b for a, b in zip(leaves_a, leaves_b)):
        print("FAIL: shared host param buffers are copies, not the same objects")
        ok = False
    fid.update(img_batches[1], real=True)
    fid.update(img_batches[3], real=False)
    kid.update(img_batches[1], real=True)
    kid.update(img_batches[3], real=False)
    float(fid.compute())
    kid.compute()

    # ---- 3. zero steady compiles: replay the warm traffic mix
    for host, batches in ((single, img_batches), (hybrid, img_batches)):
        warm = host.aot.misses
        for imgs in batches:
            host.infer(imgs)
        steady = host.aot.misses - warm
        if steady != 0:
            print(f"FAIL: warm {host.kind} host compiled {steady} programs (expected 0)")
            ok = False
    warm = pipe.aot.misses
    pipe.infer(ids, np.ones_like(ids))
    if pipe.aot.misses - warm != 0:
        print("FAIL: warm pipeline host recompiled on replay")
        ok = False

    # ---- 4a. collective allowance: the named rule, same path as make analyze
    for tag, host in (("single", single), ("hybrid", hybrid), ("pipeline", pipe)):
        findings = check_host_collectives_pinned(host, where=f"model-smoke/{tag}")
        if findings:
            for f in findings:
                print(f"FAIL: {f.render()}")
            ok = False

    # ---- 4b. OpenMetrics model_host_* families through the strict parser
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
    from tools.trace_export import parse_openmetrics

    fams = parse_openmetrics(hybrid.metrics_text())
    req = fams.get("metrics_tpu_model_host_requests")
    precisions = (
        {s["labels"].get("precision") for s in req["samples"]} if req else set()
    )
    if precisions != {"f32"}:
        print(f"FAIL: model_host_requests precision labels wrong: {precisions}")
        ok = False
    for fam in ("items", "coalesced_batches", "bucket_hits", "bucket_compiles"):
        if f"metrics_tpu_model_host_{fam}" not in fams:
            print(f"FAIL: model_host_{fam} family missing from the exposition")
            ok = False
    if "metrics_tpu_model_host_imgs_per_s" not in fams:
        print("FAIL: imgs_per_s gauge missing from the exposition")
        ok = False

    # ---- 5. kill/resume with a host attached: snapshot mid-stream, restore
    # into a FRESH engine + FRESH host, replay the remainder -> bit-identical
    feat_batches = [
        (np.asarray(single.infer(imgs)).mean(axis=1), np.linspace(0.0, 1.0, imgs.shape[0]).astype(np.float32))
        for imgs in img_batches
    ]
    snapdir = tempfile.mkdtemp(prefix="model_smoke_")
    cfg = EngineConfig(buckets=(8,), snapshot_every=3, snapshot_dir=snapdir, coalesce=1)
    eng = StreamingEngine(MeanSquaredError(), cfg)
    eng.model_host = single
    with eng:
        for f, t in feat_batches:
            eng.submit(f, t)
        want_mse = float(eng.result())
    fresh_host = inception_host(
        "2048", params, config=ModelHostConfig(buckets=(8,), coalesce_window_ms=0.0),
        shared=False,
    )
    fresh = StreamingEngine(MeanSquaredError(), cfg)
    fresh.model_host = fresh_host
    meta = fresh.restore(snapdir)
    done = int(meta["batches_done"])
    if not 0 < done < len(feat_batches):
        print(f"FAIL: snapshot covers {done} batches — kill point not mid-stream")
        ok = False
    with fresh:
        for imgs in img_batches[done:]:
            f = np.asarray(fresh_host.infer(imgs)).mean(axis=1)
            t = np.linspace(0.0, 1.0, imgs.shape[0]).astype(np.float32)
            fresh.submit(f, t)
        resumed_mse = float(fresh.result())
    if resumed_mse != want_mse:
        print(f"FAIL: kill/resume with a host attached diverged: {resumed_mse} vs {want_mse}")
        ok = False

    telemetry = {
        "single": single.telemetry(),
        "hybrid": hybrid.telemetry(),
        "pipeline": pipe.telemetry(),
        "shared": fid.model_host.telemetry(),
    }
    if out_path:
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w") as fh:
            json.dump(telemetry, fh, indent=2, sort_keys=True)

    for h in (single, hybrid, pipe, fresh_host):
        h.close()
    reset_host_registry()

    if ok:
        print(
            "model-smoke PASS: single f32 host bit-exact vs module forward, hybrid "
            "8-way stem-tensor parity, pipeline encoder bit-exact vs sequential "
            "stages, FID+KID share one resident model (params shared), zero steady "
            "compiles on warm replay, host-collectives-pinned clean, model_host_* "
            "OpenMetrics strict-parse OK, kill/resume with a host attached exact"
            + (f", telemetry -> {out_path}" if out_path else "")
        )
    return 0 if ok else 1


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    out_path = sys.argv[1] if len(sys.argv) > 1 else None
    if len(jax.devices()) < NUM_DEVICES:
        return _bootstrap()
    return _impl(out_path)


if __name__ == "__main__":
    sys.exit(main())
