"""Engine smoke check: ``python -m metrics_tpu.engine.smoke [telemetry.json]``.

The CI-shaped proof of the engine's three core claims, in seconds on one CPU
device (``make engine-smoke``):

1. correctness — streaming ragged batches through bucketed masked updates
   (with state arenas and megabatch coalescing at their serving defaults)
   equals the plain eager update loop;
2. closed program set — the first run compiles at most ``len(buckets)`` update
   programs (+1 compute), the warm second run compiles NOTHING (in-process
   AOT cache hit on every step);
3. the JAX persistent compilation cache dir is populated, so a warm process
   restart skips XLA compiles too;
4. the arena invariant — the carried state packs to ≤ 3 donated buffers
   (one per dtype class), however many metrics the collection serves.

Writes the second run's telemetry JSON (pretty-print with
``tools/engine_report.py``) and prints one PASS line. Exits nonzero on any
violated claim.
"""
import os
import sys
import tempfile

import numpy as np


def main(out_path: str = "engine_telemetry.json") -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import AotCache, EngineConfig, StreamingEngine
    from metrics_tpu.engine.aot import persistent_cache_entries

    buckets = (8, 32)
    rng = np.random.RandomState(0)
    batches = [
        (rng.rand(n).astype(np.float32), (rng.rand(n) > 0.5).astype(np.int32))
        for n in (5, 17, 8, 32, 3, 70)
    ]

    eager = MetricCollection([Accuracy(), MeanSquaredError()])
    for p, t in batches:
        eager.update(p, t)
    want = {k: float(v) for k, v in eager.compute().items()}

    cache_dir = tempfile.mkdtemp(prefix="metrics_tpu_xla_cache_")
    cache = AotCache(cache_dir=cache_dir)

    def run() -> dict:
        engine = StreamingEngine(
            MetricCollection([Accuracy(), MeanSquaredError()]),
            EngineConfig(buckets=buckets, telemetry_capacity=64),
            aot_cache=cache,
        )
        with engine:
            for p, t in batches:
                engine.submit(p, t)
            got = {k: float(v) for k, v in engine.result().items()}
        engine.export_telemetry(out_path)
        return got

    got_cold = run()
    cold_misses = cache.misses
    got_warm = run()
    warm_misses = cache.misses - cold_misses

    ok = True
    # arena invariant: the whole collection's state packs to one donated
    # buffer per dtype class (ISSUE 3 tentpole)
    layout = MetricCollection([Accuracy(), MeanSquaredError()]).arena_layout()
    if layout.num_buffers > 3 or layout.num_leaves <= layout.num_buffers:
        print(f"FAIL: arena invariant broken (no per-dtype collapse): {layout!r}")
        ok = False
    for k, v in want.items():
        if abs(got_cold[k] - v) > 1e-6 or abs(got_warm[k] - v) > 1e-6:
            print(f"FAIL: {k} engine={got_cold[k]}/{got_warm[k]} eager={v}")
            ok = False
    # cold: at most one update program per bucket + one compute program
    if cold_misses > len(buckets) + 1:
        print(f"FAIL: cold run compiled {cold_misses} programs (> {len(buckets) + 1})")
        ok = False
    if warm_misses != 0:
        print(f"FAIL: warm run compiled {warm_misses} programs (expected 0)")
        ok = False
    persisted = persistent_cache_entries(cache_dir)
    if persisted == 0:
        print("WARN: persistent compilation cache wrote no entries (backend unsupported?)")
    if ok:
        print(
            f"engine-smoke PASS: {len(batches)} ragged batches == eager; "
            f"cold compiles={cold_misses} (cap {len(buckets) + 1}), warm compiles=0, "
            f"arena buffers={layout.num_buffers} (cap 3), "
            f"persistent cache entries={persisted}; telemetry -> {out_path}"
        )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:2]))
