"""Shape bucketing + padding policy: a CLOSED set of compiled batch shapes.

Serving traffic arrives ragged; XLA programs are shape-monomorphic. Without a
policy, every new batch size is a fresh trace + compile (the reference's eager
contract has the same pathology one level down — every ``update`` re-dispatches
per shape). The policy here rounds every incoming batch up to the smallest of
a small, configurable set of bucket sizes, padding with an inert fill and a
validity mask; batches larger than the biggest bucket are split into
max-bucket chunks plus a bucketed remainder. The compiled-program set is then
at most ``len(buckets)`` per input signature, forever.

Pad rows must contribute nothing: the engine feeds the mask to
``Metric.update_state_masked`` (see ``metric.py``), which substitutes each
state reduction's identity element for masked-out rows — so correctness does
not depend on the fill value. The fill only has to be VALID input (pass the
metric's own range/type checks); 0 is right for classification targets,
probabilities, and regression values alike, and is overridable per policy.
"""
import bisect
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from metrics_tpu.utils.data import infer_batch_size, is_batch_leaf

__all__ = ["BucketPolicy"]


class BucketPolicy:
    """Round ragged batch sizes to a fixed ascending set of padded sizes.

    Args:
        buckets: allowed padded batch sizes (deduplicated, sorted ascending).
        pad_value: scalar fill for pad rows (cast to each leaf's dtype).
        divisor: every bucket must be divisible by this (the mesh batch-axis
            size for sharded engine steps; 1 for single-device).
    """

    def __init__(self, buckets: Sequence[int], pad_value: Any = 0, divisor: int = 1):
        sizes = sorted({int(b) for b in buckets})
        if not sizes or sizes[0] <= 0:
            raise ValueError(f"buckets must be positive ints, got {buckets!r}")
        bad = [b for b in sizes if b % divisor]
        if bad:
            raise ValueError(
                f"bucket sizes {bad} are not divisible by the mesh batch-axis size {divisor}"
            )
        self.buckets: Tuple[int, ...] = tuple(sizes)
        self.pad_value = pad_value
        self.divisor = int(divisor)

    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (the biggest bucket for oversized chunks)."""
        if n <= 0:
            raise ValueError(f"batch size must be positive, got {n}")
        i = bisect.bisect_left(self.buckets, n)
        return self.buckets[i] if i < len(self.buckets) else self.buckets[-1]

    def chunks(self, n: int) -> List[Tuple[int, int, int]]:
        """Split a batch of ``n`` rows into ``(start, stop, bucket)`` chunks.

        Whole max-bucket chunks first, then one bucketed remainder — so a
        10_000-row batch against buckets (256, 1024) becomes nine exact 1024
        chunks plus one 784-row chunk padded to 1024.
        """
        top = self.buckets[-1]
        out: List[Tuple[int, int, int]] = []
        start = 0
        while n - start > top:
            out.append((start, start + top, top))
            start += top
        out.append((start, n, self.bucket_for(n - start)))
        return out

    def pad_chunk(
        self, args: Tuple[Any, ...], kwargs: Dict[str, Any], start: int, stop: int, bucket: int
    ) -> Tuple[Tuple[Any, ...], Dict[str, Any], np.ndarray]:
        """Slice rows ``[start, stop)`` out of every batch-carried leaf and pad
        to ``bucket`` rows. Host-side numpy (this runs on the ingest thread,
        overlapping the device step); returns ``(args, kwargs, mask)``.

        A leaf is batch-carried when it is an array whose leading dimension
        equals the batch size inferred from the first array leaf — the same
        contract as ``Metric.update_state_masked``. Non-array leaves (python
        scalars, None) pass through untouched.
        """
        import jax

        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        n = infer_batch_size(leaves)
        if n is None:
            raise ValueError("no array argument with a leading batch dimension")
        valid = stop - start
        if not (0 < valid <= bucket):
            raise ValueError(f"chunk [{start}:{stop}) does not fit bucket {bucket}")
        # downstream, is_batch_leaf (utils/data.py) classifies leading-dim ==
        # mask length as batch-carried — against the GLOBAL bucket in the
        # 1-device step, and against the PER-SHARD row count (bucket/divisor)
        # inside a mesh step's shard_map body. A broadcast leaf of either size
        # would be silently vmapped per-row (and mesh-sharded): refuse.
        ambiguous = {bucket, bucket // self.divisor} - {n}
        out_leaves = []
        for leaf in leaves:
            if is_batch_leaf(leaf, n):
                rows = np.asarray(leaf[start:stop])
                if valid < bucket:
                    pad = np.full((bucket - valid,) + rows.shape[1:], self.pad_value, rows.dtype)
                    rows = np.concatenate([rows, pad], axis=0)
                out_leaves.append(rows)
            else:
                if any(is_batch_leaf(leaf, a) for a in ambiguous):
                    raise ValueError(
                        f"non-batch array argument with leading dimension {leaf.shape[0]} is "
                        f"ambiguous against bucket {bucket} (batch size here is {n}, "
                        f"per-shard rows {bucket // self.divisor}); reshape it (e.g. add a "
                        "leading axis of 1) or choose buckets that cannot collide"
                    )
                out_leaves.append(leaf)
        mask = np.zeros((bucket,), bool)
        mask[:valid] = True
        a, kw = jax.tree_util.tree_unflatten(treedef, out_leaves)
        return a, kw, mask

    @staticmethod
    def waste_fraction(valid_total: int, padded_total: int) -> float:
        """Fraction of device rows spent on padding (0 = perfect packing)."""
        return 0.0 if padded_total == 0 else 1.0 - valid_total / padded_total

    def __repr__(self) -> str:
        return f"BucketPolicy(buckets={self.buckets}, divisor={self.divisor})"
