"""AOT compilation cache: lower + compile each engine program exactly once.

Two layers of caching:

* **In-process executable cache** (:class:`AotCache`): engine programs are
  compiled via ``jax.jit(...).lower(...).compile()`` — explicit AOT, not
  trace-on-first-call — and memoised under a structural key (program kind,
  metric fingerprint, state/input signature, mesh fingerprint, donation,
  backend). Hit/miss counters are the serving observable: a steady-state
  stream MUST show zero misses after warmup, and the engine tests assert
  exactly that (first run: at most ``len(buckets)`` update misses; warm second
  run: zero).
* **JAX persistent compilation cache** (:func:`enable_persistent_compilation_cache`):
  pointing it at a directory makes a warm PROCESS RESTART skip the XLA compile
  too — the in-process cache counts a miss (the executable object must be
  rebuilt) but XLA serves the binary from disk instead of recompiling
  (arXiv:2605.25645's serving recipe: compile once, restart free). Enabling is
  safe at ANY point in the process lifetime: the lazily-created cache handle is
  re-initialised automatically when the backend already compiled something, so
  an engine brought up after warmup traffic still gets a populated cache dir.

The structural key deliberately excludes object identity so two engines over
equivalently-configured metrics share executables. A metric's fingerprint
covers its class tree, scalar config, and (hashed) small config arrays —
see :func:`metric_fingerprint`.
"""
import hashlib
import os
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

__all__ = ["AotCache", "enable_persistent_compilation_cache", "metric_fingerprint"]

# config arrays larger than this are fingerprinted by shape/dtype + a
# head+tail content sample instead of full content (hashing an embedded
# model's 100MB params per engine build would dominate startup)
_HASH_ARRAY_BYTES_CAP = 1 << 20


def enable_persistent_compilation_cache(path: str) -> str:
    """Point JAX's persistent compilation cache at ``path`` (process-global).

    Also drops the min-compile-time/min-entry-size thresholds so the small
    per-bucket metric programs are cached at all (the defaults only persist
    programs that took >1 s to compile). Returns the absolute path. Safe to
    call repeatedly AND at any point in the process lifetime: JAX creates the
    cache handle lazily at the backend's first compile and never re-reads the
    config, so if any computation already ran (warmup traffic, eager
    validation) the handle is re-initialised here — callers never need to
    touch ``cc.reset_cache()`` themselves. Failures (unsupported backend/jax
    build) are non-fatal — the engine still works, warm restarts just pay the
    XLA compile.
    """
    import jax

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:  # pragma: no cover - jax-version dependent
        return path
    try:
        # drop any handle created before this config took effect; the next
        # compile re-creates it against `path`. reset_cache() is safe when no
        # handle exists yet, so call unconditionally rather than probing
        # version-dependent internals.
        from jax.experimental.compilation_cache import compilation_cache as cc

        cc.reset_cache()
    except Exception:  # pragma: no cover - jax-version dependent
        pass
    return path


def persistent_cache_entries(path: Optional[str]) -> int:
    """Number of compiled-program files under a persistent cache dir."""
    if not path or not os.path.isdir(path):
        return 0
    return sum(len(files) for _, _, files in os.walk(path))


def _fingerprint_value(v: Any, h: "hashlib._Hash") -> None:
    if isinstance(v, (bool, int, float, str, bytes, type(None))):
        h.update(repr(v).encode())
    elif isinstance(v, np.generic):  # numpy scalars are NOT python ints/floats
        h.update(f"{v.dtype}:{v!r}".encode())
    elif isinstance(v, np.ndarray) or type(v).__name__ in ("ArrayImpl", "Array"):
        arr = np.asarray(v)
        h.update(f"arr{arr.shape}{arr.dtype}".encode())
        if arr.nbytes <= _HASH_ARRAY_BYTES_CAP:
            h.update(np.ascontiguousarray(arr).tobytes())
        else:
            # big config arrays (embedded-model params): hash a deterministic
            # head+tail sample instead of full content — never id(), whose
            # CPython reuse after GC could alias two different weight sets
            flat = arr.reshape(-1)
            h.update(np.ascontiguousarray(flat[:1024]).tobytes())
            h.update(np.ascontiguousarray(flat[-1024:]).tobytes())
            h.update(str(arr.nbytes).encode())
    elif isinstance(v, (tuple, list)):
        h.update(b"[")
        for x in v:
            _fingerprint_value(x, h)
        h.update(b"]")
    elif isinstance(v, dict):
        for k, val in sorted(v.items(), key=lambda kv: str(kv[0])):
            h.update(str(k).encode())
            _fingerprint_value(val, h)
    else:
        # unknown config type: hashing NOTHING here would let two differently-
        # configured metrics share a fingerprint (silently wrong program
        # reuse). repr() may be identity-unstable, which at worst costs an
        # extra compile — the safe failure direction.
        h.update(repr(v)[:256].encode())


def metric_fingerprint(metric: Any) -> str:
    """Structural fingerprint of a metric/collection's compiled behavior.

    Covers the class tree and every configuration attribute that gets baked
    into a trace: scalars, strings, small arrays (content-hashed), nested
    metrics, collection membership. Registered STATE values are excluded —
    state travels as a program argument, not a constant.
    """
    h = hashlib.sha256()

    def visit(m: Any) -> None:
        h.update(type(m).__name__.encode())
        if hasattr(m, "_defaults"):  # a Metric
            skip = set(m._defaults) | {
                "update", "compute", "_defaults", "_persistent", "_reductions",
                "_computed", "_forward_cache", "_cache", "_deferred_errcode",
                "_fwd_path_ok", "_update_called", "_is_synced", "_to_sync",
                "_should_unsync",
            }
            for name in sorted(m.__dict__):
                if name in skip:
                    continue
                v = m.__dict__[name]
                h.update(name.encode())
                if hasattr(v, "_defaults"):
                    visit(v)
                elif isinstance(v, (list, tuple)) and v and all(hasattr(x, "_defaults") for x in v):
                    for x in v:
                        visit(x)
                elif callable(v):
                    h.update(getattr(v, "__qualname__", repr(type(v))).encode())
                else:
                    _fingerprint_value(v, h)
        elif isinstance(m, dict):  # a MetricCollection
            for k, v in m.items():
                h.update(k.encode())
                visit(v)

    visit(metric)
    return h.hexdigest()[:16]


def _mesh_fingerprint(mesh: Any) -> str:
    if mesh is None:
        return "none"
    # device ids matter: an executable is compiled FOR its devices — two
    # same-shape meshes over different device subsets must not share programs
    ids = ",".join(str(d.id) for d in mesh.devices.flat)
    return f"{tuple(mesh.axis_names)}x{tuple(mesh.devices.shape)}:{mesh.devices.flat[0].platform}:{ids}"


class AotCache:
    """In-process cache of AOT-compiled engine executables, with counters.

    Args:
        cache_dir: optional directory for JAX's persistent compilation cache
            (warm process restarts skip the XLA compile).
    """

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = enable_persistent_compilation_cache(cache_dir) if cache_dir else None
        self._programs: Dict[Tuple, Any] = {}
        # one cache may be SHARED across engines (each with its own dispatcher
        # thread); the lock also spans build(), so two threads racing the same
        # key pay ONE compile, not two, and the counters stay exact
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.compile_seconds = 0.0

    def __len__(self) -> int:
        return len(self._programs)

    def program_keys(self) -> Tuple[Tuple, ...]:
        """Snapshot of every compiled program's structural key — the
        program-plane analyzer's accounting hook (``compile-cap`` attributes
        a shared cache's programs to engines by fingerprint/mesh/sync)."""
        with self._lock:
            return tuple(self._programs)

    def count_hit(self) -> None:
        """Atomically count a cache hit served from an engine-local memo."""
        with self._lock:
            self.hits += 1

    def contains(self, key: Tuple) -> bool:
        """Whether ``key`` already holds a compiled program. No counter is
        touched — this is the attribution probe for callers that need to
        know if THEIR lookup will compile (a delta of the shared ``misses``
        counter would blame another engine's concurrent compile on them)."""
        with self._lock:
            return key in self._programs

    def enable_persistent_cache(self, path: str) -> str:
        """Turn the persistent compilation cache on MID-PROCESS (the backend
        may already have compiled programs — the stale cache handle is reset
        automatically). Programs compiled from now on land under ``path``."""
        with self._lock:
            self.cache_dir = enable_persistent_compilation_cache(path)
            return self.cache_dir

    def get_or_compile(self, key: Tuple, build: Callable[[], Any]) -> Any:
        """Return the executable for ``key``, compiling via ``build()`` on miss."""
        with self._lock:
            prog = self._programs.get(key)
            if prog is not None:
                self.hits += 1
                return prog
            self.misses += 1
            t0 = time.perf_counter()
            prog = build()
            self.compile_seconds += time.perf_counter() - t0
            self._programs[key] = prog
            return prog

    @staticmethod
    def signature_of(tree: Any) -> Tuple:
        """Hashable (treedef, leaf shape/dtype) signature of an arg pytree."""
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        sig = tuple(
            (leaf.shape, str(leaf.dtype))
            if hasattr(leaf, "shape")
            else (
                (type(leaf).__name__, leaf)
                if isinstance(leaf, (bool, int, float, str, type(None)))
                else (type(leaf).__name__, repr(leaf)[:64])  # fail-safe: key by repr
            )
            for leaf in leaves
        )
        return (treedef, sig)

    def program_key(
        self,
        kind: str,
        metric_fp: str,
        arg_tree: Any = None,
        mesh: Any = None,
        donate: bool = False,
        sync: str = "step",
        precision: str = "exact",
    ) -> Tuple:
        """Structural program identity. ``sync`` is the engine's mesh sync
        mode (``"step"`` merges shard deltas inside every step; ``"deferred"``
        carries shard-local state and merges at boundaries): the two modes
        lower DIFFERENT programs over the same payload signature — update
        programs differ in collectives, and the deferred mode adds separate
        ``merge`` entries — so the mode is part of every key and engines in
        different modes sharing one cache never exchange executables.

        ``precision`` is the metric's ``sync_precision_tag()`` (ISSUE 10):
        quantized and exact policies lower different collective bundles over
        identical state signatures (int8 riders vs f32 psum), so the policy
        is part of EVERY key — the fingerprint covers it too, but the
        explicit component keeps the contract visible and un-regressable."""
        import jax

        return (
            kind,
            metric_fp,
            self.signature_of(arg_tree) if arg_tree is not None else None,
            _mesh_fingerprint(mesh),
            bool(donate),
            str(sync),
            jax.default_backend(),
            str(precision),
        )

    def stats(self) -> Dict[str, Any]:
        return {
            "programs": len(self._programs),
            "hits": self.hits,
            "misses": self.misses,
            "compile_seconds": round(self.compile_seconds, 3),
            "persistent_cache_dir": self.cache_dir,
            "persistent_cache_entries": persistent_cache_entries(self.cache_dir),
        }
