"""Ragged-serving smoke check: ``python -m metrics_tpu.engine.ragged_smoke``.

The CPU-safe gate for the ISSUE 17 ragged stack (``make ragged-smoke``), on
the bootstrap 8-device virtual mesh:

1. retrieval — ``RetrievalMAP`` group-keyed traffic through a DEFERRED mesh
   ``RaggedEngine`` serves the aggregate bit-exact vs the eager oracle, with
   zero steady-state compiles over a ``reset()`` + replay of the same plan;
2. detection — ``MeanAveragePrecision`` through the engine: every result key
   equals the eager oracle exactly, and the per-image occupancy read serves;
3. kill/resume — snapshot mid-plan, a fresh engine restores and replays the
   remainder to the exact straight-through value (and a non-ragged snapshot
   is REFUSED with the typed provenance message);
4. composition — ``WindowPolicy`` + ``group_shard`` (the stream-shard pager
   at group grain, resident cap below the group count) together still serve
   the aggregate bit-exact;
5. aggregate reads (ISSUE 18) — the device aggregate equals the host oracle
   bit-exact at G=512 on the mesh IN ONE device dispatch, and a forced-spill
   ``group_shard`` engine sweeps the same value in O(touched/block) paged
   blocks — dispatch count never scales with the group universe;
6. refusals — the plain engine refuses the cat-list metric at construction
   with the typed pointer at the ragged path, and the ragged engine's
   programs audit clean under the full analysis rule set.

The ingest plan carries DELIBERATE equal sort keys: ``grouped_finalize``
reconstructs each group's rows in ingest-rank order (the engine-owned
``_seq`` tie-break), so ties are bit-exact across shard/pane interleavings.

Prints one PASS line; exits nonzero on any violated claim.
"""
import os
import subprocess
import sys

NUM_DEVICES = 8


def _bootstrap() -> int:
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={NUM_DEVICES}"
    )
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import sys; from metrics_tpu.engine.ragged_smoke import _impl; sys.exit(_impl())"
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env, timeout=900)
    return proc.returncode


def _impl() -> int:
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from metrics_tpu import RetrievalMAP
    from metrics_tpu.detection import MeanAveragePrecision
    from metrics_tpu.engine import (
        AotCache,
        EngineConfig,
        RaggedEngine,
        StreamingEngine,
        WindowPolicy,
    )
    from metrics_tpu.utils.exceptions import MetricsTPUUserError

    devs = jax.devices()
    if len(devs) < NUM_DEVICES:
        print(f"FAIL: need {NUM_DEVICES} devices, have {len(devs)}")
        return 1
    mesh = Mesh(np.asarray(devs[:NUM_DEVICES]), ("dp",))
    ok = True
    GROUPS, CAP, ROWS, BATCHES = 12, 32, 16, 6

    # seeded plan with DELIBERATE pred ties: grouped_finalize reconstructs
    # each group's rows in ingest-rank order (the engine-owned _seq
    # tie-break), so equal sort keys stay bit-exact across every shard/pane
    # interleaving — no distinct-key restriction needed
    rng = np.random.RandomState(17)
    vals = np.round(rng.rand(BATCHES * ROWS), 1).astype(np.float32)
    plan = []
    for b in range(BATCHES):
        plan.append((
            vals[b * ROWS:(b + 1) * ROWS],
            rng.randint(0, 2, ROWS).astype(np.int64),
            rng.randint(0, GROUPS, ROWS),
        ))

    def oracle():
        m = RetrievalMAP()
        for p, t, g in plan:
            m.update(jnp.asarray(p), jnp.asarray(t), indexes=jnp.asarray(g))
        return float(m.compute())

    want = oracle()

    # ---- 1. deferred-mesh retrieval parity + zero steady compiles
    cache = AotCache()
    eng = RaggedEngine(
        RetrievalMAP(), num_groups=GROUPS,
        config=EngineConfig(buckets=(ROWS,), mesh=mesh, axis="dp",
                            mesh_sync="deferred"),
        capacity=CAP, aot_cache=cache,
    )
    with eng:
        for p, t, g in plan:
            eng.submit_update(p, t, g)
        got = float(eng.result())
        warm = cache.misses
        eng.reset()
        for p, t, g in plan:
            eng.submit_update(p, t, g)
        eng.flush()
        steady = cache.misses - warm
    if got != want:
        print(f"FAIL: deferred-mesh retrieval aggregate {got!r} != eager oracle {want!r}")
        ok = False
    if steady != 0:
        print(f"FAIL: steady-state replay compiled {steady} programs (expected 0)")
        ok = False

    # ---- 2. detection MAP through the engine, exact vs eager oracle
    dr = np.random.RandomState(5)
    preds, target = [], []
    for _ in range(4):
        nd, ng = dr.randint(1, 5), dr.randint(1, 4)
        pb = dr.rand(nd, 4).astype(np.float32) * 60
        pb[:, 2:] += pb[:, :2] + 4
        gb = dr.rand(ng, 4).astype(np.float32) * 60
        gb[:, 2:] += gb[:, :2] + 4
        preds.append({"boxes": pb,
                      "scores": dr.permutation(nd * 9)[:nd].astype(np.float32) / (nd * 9),
                      "labels": dr.randint(0, 3, nd)})
        target.append({"boxes": gb, "labels": dr.randint(0, 3, ng)})
    om = MeanAveragePrecision()
    om.update(preds, target)
    want_det = {k: np.asarray(v) for k, v in om.compute().items()}
    det = RaggedEngine(MeanAveragePrecision(), num_groups=4,
                       config=EngineConfig(buckets=(64,)), capacity=64)
    with det:
        det.submit_update(preds, target, image_ids=np.arange(4))
        got_det = {k: np.asarray(v) for k, v in det.result().items()}
        occ = det.result(2)
    for k in want_det:
        if not np.array_equal(got_det[k], want_det[k]):
            print(f"FAIL: detection key {k}: served {got_det[k]} != oracle {want_det[k]}")
            ok = False
    if int(occ["detections"]) != len(preds[2]["boxes"]):
        print(f"FAIL: per-image occupancy read wrong: {occ}")
        ok = False

    # ---- 3. kill/resume exact + cross-kind restore refusal
    snapdir = tempfile.mkdtemp(prefix="ragged_smoke_")

    def _cfg():
        return EngineConfig(buckets=(ROWS,), snapshot_dir=snapdir)

    first = RaggedEngine(RetrievalMAP(), num_groups=GROUPS, config=_cfg(), capacity=CAP)
    with first:
        for p, t, g in plan[:3]:
            first.submit_update(p, t, g)
        first.flush()
        first.snapshot()
    resumed = RaggedEngine(RetrievalMAP(), num_groups=GROUPS, config=_cfg(), capacity=CAP)
    with resumed:
        resumed.restore()
        for p, t, g in plan[3:]:
            resumed.submit_update(p, t, g)
        got_resumed = float(resumed.result())
    if got_resumed != want:
        print(f"FAIL: kill/resume replay {got_resumed!r} != straight-through {want!r}")
        ok = False
    plaindir = tempfile.mkdtemp(prefix="ragged_smoke_plain_")
    from metrics_tpu import Accuracy

    plain = StreamingEngine(Accuracy(), EngineConfig(buckets=(8,), snapshot_dir=plaindir))
    with plain:
        plain.submit(np.asarray([0.1, 0.9], np.float32), np.ones(2, np.int32))
        plain.flush()
        plain.snapshot()
    wrong = RaggedEngine(RetrievalMAP(), num_groups=GROUPS,
                         config=EngineConfig(buckets=(ROWS,), snapshot_dir=plaindir),
                         capacity=CAP)
    try:
        wrong.restore()
        print("FAIL: a non-ragged snapshot restored into a RaggedEngine")
        ok = False
    except MetricsTPUUserError:
        pass
    finally:
        wrong.stop()

    # ---- 4. windows + group_shard composition on the mesh
    comp = RaggedEngine(
        RetrievalMAP(), num_groups=GROUPS,
        config=EngineConfig(buckets=(ROWS,), mesh=mesh, axis="dp",
                            mesh_sync="deferred",
                            window=WindowPolicy.tumbling(pane_batches=1000)),
        capacity=CAP, group_shard=True, resident_groups=3,
    )
    with comp:
        for p, t, g in plan:
            comp.submit_update(p, t, g)
        got_comp = float(comp.result())
    if got_comp != want:
        print(f"FAIL: windows+group_shard aggregate {got_comp!r} != oracle {want!r}")
        ok = False

    # ---- 5. aggregate reads (ISSUE 18): device/host parity at G=512 on the
    # mesh, one paged sweep through a forced spill, and the O(1)-dispatch pin
    AGG_G = 512
    ar = np.random.RandomState(29)
    agg_rows = 4 * AGG_G
    agg_gids = (np.arange(agg_rows) % AGG_G).astype(np.int32)
    agg_p = np.round(ar.rand(agg_rows), 2).astype(np.float32)  # ties on purpose
    agg_t = (ar.rand(agg_rows) > 0.5).astype(np.float32)
    agg = RaggedEngine(
        RetrievalMAP(), num_groups=AGG_G,
        config=EngineConfig(buckets=(agg_rows,), mesh=mesh, axis="dp",
                            mesh_sync="deferred"),
        capacity=8,
    )
    with agg:
        agg.submit(agg_gids, agg_p, agg_t)
        agg.flush()
        path, why = agg.aggregate_path()
        calls0 = agg.stats.result_device_calls
        got_dev = float(agg.aggregate())
        dispatches = agg.stats.result_device_calls - calls0
        got_host = float(agg.aggregate(oracle=True))
    if path != "device":
        print(f"FAIL: G={AGG_G} aggregate routed {path!r} ({why}), expected device")
        ok = False
    if got_dev != got_host:
        print(f"FAIL: device aggregate {got_dev!r} != host oracle {got_host!r} at G={AGG_G}")
        ok = False
    if dispatches != 1:
        print(f"FAIL: aggregate issued {dispatches} device dispatches at "
              f"G={AGG_G}, expected exactly 1 (O(1), not O(G))")
        ok = False

    paged = RaggedEngine(
        RetrievalMAP(), num_groups=AGG_G,
        config=EngineConfig(buckets=(agg_rows,), mesh=mesh, axis="dp",
                            mesh_sync="deferred"),
        capacity=8, group_shard=True, resident_groups=64,
    )
    with paged:
        paged.submit(agg_gids, agg_p, agg_t)
        paged.flush()
        blocks0 = paged.stats.ragged_summary()["agg_blocks"]
        got_paged = float(paged.aggregate())
        sweep_blocks = paged.stats.ragged_summary()["agg_blocks"] - blocks0
    if got_paged != got_host:
        print(f"FAIL: forced-spill paged aggregate {got_paged!r} != host "
              f"oracle {got_host!r}")
        ok = False
    if not (1 <= sweep_blocks < AGG_G):
        print(f"FAIL: paged sweep ran {sweep_blocks} blocks for {AGG_G} touched "
              "groups — dispatch count must scale with touched/block, not G")
        ok = False

    # ---- 6. typed refusal + program audit
    try:
        StreamingEngine(RetrievalMAP(), EngineConfig(buckets=(8,)))
        print("FAIL: plain engine accepted a cat-list retrieval metric")
        ok = False
    except MetricsTPUUserError as e:
        if "RaggedEngine" not in str(e):
            print(f"FAIL: refusal does not point at the ragged path: {e}")
            ok = False
    from metrics_tpu.analysis import EngineAnalysis

    findings = EngineAnalysis().check(eng, label="ragged-smoke/deferred").findings
    if findings:
        for f in findings:
            print(f"FAIL: {f.render()}")
        ok = False

    if ok:
        print(
            f"ragged-smoke PASS: RetrievalMAP bit-exact through the deferred "
            f"{NUM_DEVICES}-dev mesh ({GROUPS} groups, capacity {CAP}), detection "
            "MAP exact vs the eager oracle, kill/resume replay exact (cross-kind "
            "restore refused), windows+group_shard composition exact, device "
            f"aggregate == host oracle at G={AGG_G} in ONE dispatch (forced-spill "
            "paged sweep exact, O(touched/block) blocks), plain-engine refusal "
            "typed, program audit clean, zero steady compiles"
        )
    return 0 if ok else 1


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if len(jax.devices()) < NUM_DEVICES:
        return _bootstrap()
    return _impl()


if __name__ == "__main__":
    sys.exit(main())
