"""The streaming engine: bounded ingest queue → padded buckets → AOT steps.

Dataflow (one engine = one metric/collection served as a stream consumer)::

    submit(*batch)        # producer thread(s); BLOCKS when the queue is full
      └─ bounded queue (backpressure, config.max_queue batches)
           └─ dispatcher thread: drain ≤ coalesce compatible batches →
              concat (megabatch) → chunk → pad to bucket (host numpy) →
              device upload → AOT-compiled step(arena, batch, mask)
                 └─ donated per-dtype state arenas, up to config.in_flight
                    steps un-synced (JAX async dispatch overlaps the host's
                    padding of batch k+1 with the device's execution of k)
    result()              # flush + AOT-compiled compute on the final state

Design notes:

* **Closed program set.** Every step program is keyed by (bucket signature,
  metric fingerprint, mesh, donation, backend) and compiled ahead-of-time via
  ``jit(...).lower(...).compile()`` — after at most ``len(buckets)`` compiles
  per input signature the engine never traces again (``engine/aot.py``).
  Coalescing and arenas do not widen the set: a megabatch re-chunks into the
  same buckets, and the arena is one fixed signature per engine.
* **State arenas.** With ``config.use_arena`` (default) the carried state is
  not the per-leaf pytree but its packed form (``engine/arena.py``): ONE
  contiguous buffer per dtype, unpacked inside the jitted step with static
  slices XLA fuses away. A step dispatch then flattens/type-checks/donates
  2–3 arrays instead of one per state leaf — the difference between
  dispatch-bound and device-bound at small batch sizes.
* **Megabatch coalescing.** ``config.coalesce > 1`` lets the dispatcher
  opportunistically drain up to that many QUEUED batches whose non-batch
  arguments agree, concatenate them on the host, and run the result as one
  (bucketed) masked step — K submissions, one dispatch and one in-step
  collective set. Exactness is free: masked updates are row-exact, and the
  concatenation preserves submission order. Latency is bounded: draining
  never blocks beyond ``coalesce_window_ms`` (default 0 — only batches
  already queued coalesce), never crosses a snapshot boundary (the replay
  cursor cadence stays exact), and stops once the top bucket is filled.
* **Donation.** The state buffers are donated into each step: XLA merges the
  delta in place instead of allocating a second state copy (material for
  big-state metrics; ``metric.py`` documents the same policy for compiled
  forward). Donation is skipped on CPU, which doesn't implement it.
* **Mesh-aware steps.** With ``config.mesh`` the step runs under ``shard_map``:
  batch rows and mask shard over ``config.axis``. Two sync modes
  (``config.mesh_sync``, pinned at construction, part of every program key):

  - ``"step"`` (default): state stays replicated, the per-shard masked delta
    is psum-merged in-step (``sync_states``) so the carried state is always
    the GLOBAL state — compute needs no further sync, a snapshot between any
    two steps is globally consistent, and every steady-state step pays one
    fused cross-chip collective bundle.
  - ``"deferred"``: the reference's own laziness (per-process local
    accumulation, ``dist_reduce_fx`` merge only at compute) on a mesh. The
    carried state is SHARD-LOCAL — every buffer gains a leading shard axis
    sharded over ``config.axis`` — and the steady-state step is
    COLLECTIVE-FREE (zero psum/pmin/pmax/all_gather in its jaxpr, pinned by
    test). The merge moves to explicit boundaries (``result()``, ``state()``,
    snapshot, cross-topology restore), where the whole state rides ONE fused
    collective bundle (``parallel/collectives.py::fused_axis_sync``). Because
    the merge now acts on STATES, not per-step deltas, scan-strategy metrics
    (``AUROC(capacity=N)``'s cat-written buffers) serve on mesh: shards fold
    their own rows sequentially and the boundary merge all-gathers the
    buffers — exactly ``dist_reduce_fx="cat"``. Note capacity is then
    PER-SHARD (world x N rows fit before overflow).
* **Virtual-mesh serialization.** On CPU meshes overlapping async collective
  executions can deadlock the in-process communicator
  (``parallel/embedded.py``); the engine serializes steps there — in
  ``"step"`` mode only. Deferred steady steps carry no collectives, so even
  CPU meshes keep the full ``in_flight`` pipeline (boundary merges are
  blocked on under the state lock instead).
* **Recovery.** ``snapshot_every > 0`` writes crash-safe periodic snapshots
  (``engine/snapshot.py``); ``restore()`` resumes exactly — replaying the
  stream from the snapshot's step reproduces the uninterrupted result.
  Snapshots carry the packed arena (one payload per dtype) plus the metric's
  host-derived compute attributes (``Metric.host_compute_attrs``), so a
  restored engine computes immediately.
* **Fault tolerance** (``engine/faults.py``; docs/serving.md "Failure
  semantics"). Steps are TRANSACTIONAL: with ``config.transactional`` the
  dispatcher keeps a donation-aware shadow of the pre-step state (a free
  reference when donation is off, one device copy when it is on) and every
  step failure rolls back onto it — a poisoned batch or injected fault never
  leaves the arena torn. Pre-dispatch SCREENING (``config.screen``, the
  ``nan_strategy`` vocabulary + ``"quarantine"``) dead-letters bad batches
  into a bounded ledger instead of letting them reach a compiled step.
  Transient failures get bounded retries with seeded jittered exponential
  backoff; kernel failures demote ``pallas → xla`` (the tag is in every
  program key, so demoted programs never collide in a shared cache);
  megabatch failures shrink to singleton re-dispatch so the sticky error
  names exactly the poisoned cursor; a per-step watchdog
  (``config.step_timeout_s``) catches stuck pipelines; failed PERIODIC
  snapshots are contained (the previous generation keeps serving restore)
  and ``restore()`` falls back past corrupted generations. Every boundary is
  instrumented for the seeded chaos harness (``config.fault_injector``,
  ``make chaos-smoke``) and every recovery action is counted in
  ``engine/stats.py``.
"""
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.engine.admission import (
    AdmissionPolicy,
    AdmissionRejected,
    DegradationLadder,
)
from metrics_tpu.engine.aot import AotCache, metric_fingerprint
from metrics_tpu.engine.arena import ArenaLayout
from metrics_tpu.engine.bucketing import BucketPolicy
from metrics_tpu.engine.faults import (
    BackpressureTimeout,
    EngineDispatchError,
    FaultInjector,
    InjectedFault,
    QuarantineRecord,
    ScreenPolicy,
    StepTimeoutError,
    corrupt_snapshot,
    is_transient,
    wait_with_timeout,
)
from metrics_tpu.engine.snapshot import load_snapshot, save_snapshot
from metrics_tpu.engine.stats import EngineStats
from metrics_tpu.engine.trace import ENGINE_TRACE, TraceRecorder, render_openmetrics
from metrics_tpu.engine.tracker import DriftDetector
from metrics_tpu.engine.windows import WindowPolicy
from metrics_tpu.ops.kernels import (
    MEGASTEP_BACKENDS,
    current_backend,
    resolve_backend,
    use_backend,
)
from metrics_tpu.utils.data import infer_batch_size, is_batch_leaf
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["EngineConfig", "StreamingEngine"]

_STOP = object()

# non-batch leaves larger than this are never content-compared for megabatch
# compatibility — the comparison would cost more than the dispatch it saves
_COALESCE_AUX_COMPARE_CAP = 4096


@dataclass
class EngineConfig:
    """Configuration for :class:`StreamingEngine`.

    Args:
        buckets: allowed padded batch sizes (the closed shape set).
        max_queue: bounded ingest queue capacity, in batches. ``submit``
            blocks when full — backpressure to the producer.
        in_flight: device steps allowed un-synced before the dispatcher
            blocks on the oldest (double-buffering depth).
        coalesce: max SUBMITTED batches the dispatcher may drain and
            concatenate into one megabatch step (1 disables). Compatible
            batches only (same structure/dtypes, equal non-batch arguments);
            an incompatible batch ends the group and runs next.
        coalesce_window_ms: how long the dispatcher may WAIT for more
            coalescible traffic once the queue runs dry (0 = never wait —
            only already-queued batches coalesce, adding zero latency).
        use_arena: carry the state as per-dtype packed arenas
            (``engine/arena.py``) instead of the per-leaf pytree — fewer
            donated step arguments, one snapshot payload per dtype.
        snapshot_every: BATCHES between crash-safe state snapshots (0 = off).
            Snapshots land on batch boundaries only — a batch larger than the
            top bucket spans several device steps, and a mid-batch snapshot
            would break batch-level replay on resume. Megabatch groups never
            cross a snapshot boundary, so the cadence stays exact under
            coalescing.
        snapshot_dir: where snapshots live (required when snapshot_every > 0).
        compilation_cache_dir: JAX persistent compilation cache directory —
            warm process restarts skip XLA compiles entirely.
        kernel_backend: streaming-update kernel backend for this engine's
            compiled programs (``metrics_tpu/ops/kernels``): ``"pallas"``
            (fused TPU kernels), ``"pallas_interpret"`` (same kernel logic,
            interpreted — CPU parity testing), ``"xla"`` (the reference
            lowering), ``"auto"`` (Pallas on TPU, XLA elsewhere), or None to
            inherit the selection ambient at engine CONSTRUCTION
            (``use_backend`` context > ``set_default_backend`` >
            ``METRICS_TPU_KERNEL_BACKEND`` env var > ``"auto"``). The choice
            is PINNED at construction for every program this engine builds —
            update programs build on the dispatcher thread and compute
            programs on the caller's, and a thread-local context active at
            ``result()`` time must not split one engine across lowerings.
            Part of every program's cache identity — engines with different
            backends sharing an ``AotCache`` never exchange executables.
        mesh: optional ``jax.sharding.Mesh`` for sharded engine steps.
        axis: mesh axis name carrying the batch shards.
        mesh_sync: WHEN shard contributions merge on a mesh (ignored without
            one). ``"step"`` (default) psum-merges the per-shard deltas inside
            every step — the carried state is globally consistent at all
            times, at one cross-chip collective bundle per step. ``"deferred"``
            carries shard-LOCAL state, keeps the steady-state step free of
            collectives, and merges whole states at explicit boundaries
            (``result()``/``state()``/snapshot) with one fused collective
            bundle — the reference's per-process accumulation semantics, and
            the only mode that serves ``cat``/scan-strategy metrics (e.g.
            ``AUROC(capacity=N)``) on a mesh. Pinned at construction; part of
            every AOT program key.
        donate: donate state buffers into each step (ignored on CPU).
        pad_value: fill for pad rows (must pass the metric's input checks;
            masked out of every reduction regardless).
        telemetry_capacity: ring-buffer size for per-step telemetry.
        snapshot_keep: complete snapshots retained after GC — the GENERATION
            RING ``restore()`` falls back through when the newest payload is
            corrupt (``engine/snapshot.py``).
        fault_injector: optional seeded :class:`~metrics_tpu.engine.faults.
            FaultInjector` — the deterministic chaos harness; every engine
            boundary (ingest/coalesce/compile/step/kernel/watchdog/merge/
            snapshot) consults it. None (default) costs nothing.
        screen: optional :class:`~metrics_tpu.engine.faults.ScreenPolicy` —
            pre-dispatch batch screening (NaN/Inf, id range, batch-shape
            uniformity) with per-check actions from the ``nan_strategy``
            vocabulary plus ``"quarantine"`` (dead-letter the batch, keep
            serving). None (default) screens nothing.
        quarantine_capacity: dead-letter ledger size (newest records kept,
            payload included); lifetime counts live in ``stats``.
        max_retries: bounded retry budget for TRANSIENT failures per step /
            group / boundary merge (injected transients, watchdog expiries,
            RESOURCE_EXHAUSTED-family runtime errors). Deterministic errors
            (shape mismatches, trace failures) never retry — they go sticky
            with the failing batch context attached.
        backoff_base_ms / backoff_max_ms: jittered exponential backoff
            between retries (seeded jitter — chaos runs are replayable).
        step_timeout_s: per-step watchdog (0 = off). When armed the engine
            syncs every step before commit (trading the async pipeline for
            per-step containment) and a stuck device step rolls back and
            retries instead of wedging the dispatcher forever.
        transactional: keep a donation-aware SHADOW of the pre-step state so
            step failures roll back instead of poisoning the carry. None
            (default) auto-enables when donation is off (the shadow is a free
            reference — CPU serving is always transactional), when a
            fault_injector is present, or when the watchdog is armed
            (``step_timeout_s > 0`` — expiry recovery REQUIRES the shadow);
            with donation on, True costs one device-to-device state copy
            per step.
        degrade_kernel: demote this engine ``pallas → xla`` when a kernel-
            site fault fires (the resolved backend tag is part of every
            program key, so demotion re-compiles rather than collides).
        trace: optional :class:`~metrics_tpu.engine.trace.TraceRecorder` —
            the flight recorder. Every submitted batch gets a trace id, the
            dispatcher stamps each pipeline stage as a span (a megabatch
            span LINKS the submit spans it absorbed), every fault-site
            firing becomes an event, and ``export_trace(path)`` /
            ``metrics_text()`` expose the Perfetto and OpenMetrics views.
            None (default) costs one ``is not None`` check per site —
            nothing else (the ``obs_overhead`` bench guards this).
        admission: optional :class:`~metrics_tpu.engine.admission.
            AdmissionPolicy` — SLO-aware admission control on the submit
            path: per-stream token buckets with priority classes; a refused
            submit raises the typed :class:`~metrics_tpu.engine.admission.
            AdmissionRejected` with ``retry_after_s`` BEFORE the batch ever
            queues (the replay cursor and exactness contracts never see it).
            None (default) costs one ``is not None`` check per submit.
        ladder: optional :class:`~metrics_tpu.engine.admission.
            DegradationLadder` — the graceful-degradation policy. Once per
            dispatcher group the engine feeds the ladder's overload detector
            (p99 queue residency from the flight recorder's ``queue_wait_us``
            histogram when one is attached, the stats ring otherwise; pager
            spill rate; queue fill) and applies/releases rungs on its
            deterministic transitions: widen ``coalesce_window_ms`` → force
            ``sync_precision`` quantization for eligible states → defer
            cold-stream ``result()`` reads → shed the lowest priority class
            (needs ``admission``). Every transition is a ``ladder`` trace
            event. None (default) costs one ``is not None`` check per group.
        elastic_min_world: arm shard-loss auto-resharding: a non-transient
            ``shard_loss`` fault (the chaos model of a dead shard) triggers
            an in-place :meth:`StreamingEngine.reshard` to the largest
            bucket-compatible world below the current one, never below this
            floor — the dead shard degrades to a smaller world with the
            surviving state intact instead of a dead engine. 0 (default) =
            off: shard loss goes sticky like any other fault.
        compress_payloads: store state-at-rest through the block-scaled int8
            codec (``engine/quantize.py``): snapshot payloads carry codes +
            scales (codec id in meta, the sha256 sidecar hashes the
            COMPRESSED bytes) and stream-pager spill rows live in host RAM
            quantized. Only states the metric's ``sync_precision`` policy
            declared ``"q8_block"`` compress — counts and cat buffers stay
            verbatim, so their kill/resume replay remains bit-exact; the
            quantized states restore within the codec's declared per-element
            bound (the same ``q8_sum_error_bound`` oracle as the wire
            rider). Default off: snapshots stay byte-identical to r10.
        window: optional :class:`~metrics_tpu.engine.windows.WindowPolicy` —
            windowed/time-decayed result semantics (ISSUE 13). ``tumbling``/
            ``sliding`` turn the carried state into a RING-OF-ARENAS (one
            extra leading pane axis on the per-dtype buffers; the step
            updates a runtime-indexed pane row, so rotation never retraces);
            ``ewma`` applies a ``1 - alpha`` scale to the (sum-reducible,
            float — refused loudly otherwise) states at each rotation.
            Rotation happens at batch boundaries inside the dispatcher, on a
            ``pane_batches`` (replay-cursor-exact) or ``pane_seconds``
            (injectable clock) cadence; coalesce groups never cross a
            batch-cadence pane boundary. ``result()`` reads the current pane
            (tumbling) or folds the live pane set via
            ``merge_stacked_states`` (sliding). None/cumulative (default)
            keeps the since-reset semantics and the carried state byte-
            identical to r12. Windowed mesh serving is DEFERRED-sync only.
        drift: optional :class:`~metrics_tpu.engine.tracker.DriftDetector` —
            at every pane rotation the dispatcher evaluates the CLOSING
            pane's result (the ``drift_eval`` fault site; a pure read, so
            transients retry without double-recording) and feeds it to the
            detector; hysteresis transitions surface as ``drift_alarm``
            trace events and the ``drift_alarms`` OpenMetrics counter.
            Requires a rotating ``window``.
    """

    buckets: Tuple[int, ...] = (256, 1024)
    max_queue: int = 64
    in_flight: int = 2
    coalesce: int = 8
    coalesce_window_ms: float = 0.0
    use_arena: bool = True
    snapshot_every: int = 0
    snapshot_dir: Optional[str] = None
    compilation_cache_dir: Optional[str] = None
    kernel_backend: Optional[str] = None
    mesh: Optional[Any] = None
    axis: str = "dp"
    mesh_sync: str = "step"
    donate: bool = True
    pad_value: Any = 0
    telemetry_capacity: int = 1024
    snapshot_keep: int = 2
    fault_injector: Optional[FaultInjector] = None
    screen: Optional[ScreenPolicy] = None
    quarantine_capacity: int = 64
    max_retries: int = 2
    backoff_base_ms: float = 1.0
    backoff_max_ms: float = 50.0
    step_timeout_s: float = 0.0
    transactional: Optional[bool] = None
    degrade_kernel: bool = True
    trace: Optional[TraceRecorder] = None
    compress_payloads: bool = False
    admission: Optional[AdmissionPolicy] = None
    ladder: Optional[DegradationLadder] = None
    elastic_min_world: int = 0
    window: Optional[WindowPolicy] = None
    drift: Optional[DriftDetector] = None


class StreamingEngine:
    """Drive a ``Metric``/``MetricCollection`` as a streaming service.

    Class constant :data:`_LADDER_P99_EVERY` throttles the degradation
    ladder's p99 queue-residency refresh (the expensive signal) to one read
    per that many ticks — watermark tests don't need per-group freshness.

    Thread model: producers call :meth:`submit`; one dispatcher thread owns
    the device pipeline; :meth:`flush`/:meth:`result`/:meth:`state` join the
    queue before touching state, so reads never race the dispatcher.
    """

    _LADDER_P99_EVERY = 8

    def __init__(self, metric: Any, config: Optional[EngineConfig] = None, aot_cache: Optional[AotCache] = None):
        from dataclasses import replace

        self._metric = metric
        # PRIVATE copy of the config: reshard() swaps cfg.mesh and the
        # ladder's widen rung moves cfg.coalesce_window_ms — two engines
        # constructed from one shared EngineConfig must never see each
        # other's elasticity (shallow: injector/trace/policy objects are
        # meant to be shared; only the scalar/mesh fields are engine-owned)
        self._cfg = replace(config) if config is not None else EngineConfig()
        if self._cfg.mesh_sync not in ("step", "deferred"):
            raise MetricsTPUUserError(
                f"mesh_sync must be 'step' or 'deferred', got {self._cfg.mesh_sync!r}"
            )
        if self._cfg.mesh_sync == "deferred" and self._cfg.mesh is None:
            raise MetricsTPUUserError(
                "mesh_sync='deferred' needs a mesh: without one there are no shard-"
                "local states to defer the merge of (drop mesh_sync or set mesh)"
            )
        self._deferred = self._cfg.mesh is not None and self._cfg.mesh_sync == "deferred"
        reason = self._serving_unsupported_reason(metric)
        if reason is not None:
            raise MetricsTPUUserError(
                f"metric cannot be served by the streaming engine: {reason}"
            )
        if self._cfg.max_retries < 0:
            raise MetricsTPUUserError(
                f"max_retries must be >= 0, got {self._cfg.max_retries}"
            )
        if self._cfg.step_timeout_s < 0:
            raise MetricsTPUUserError(
                f"step_timeout_s must be >= 0, got {self._cfg.step_timeout_s}"
            )
        if self._cfg.screen is not None and not isinstance(self._cfg.screen, ScreenPolicy):
            raise MetricsTPUUserError(
                f"config.screen must be a ScreenPolicy, got {type(self._cfg.screen).__name__}"
            )
        inj = self._cfg.fault_injector
        if inj is not None and not isinstance(inj, FaultInjector):
            raise MetricsTPUUserError(
                f"config.fault_injector must be a FaultInjector, got {type(inj).__name__}"
            )
        if self._cfg.trace is not None and not isinstance(self._cfg.trace, TraceRecorder):
            raise MetricsTPUUserError(
                f"config.trace must be a TraceRecorder, got {type(self._cfg.trace).__name__}"
            )
        if self._cfg.admission is not None and not isinstance(self._cfg.admission, AdmissionPolicy):
            raise MetricsTPUUserError(
                f"config.admission must be an AdmissionPolicy, got {type(self._cfg.admission).__name__}"
            )
        if self._cfg.ladder is not None and not isinstance(self._cfg.ladder, DegradationLadder):
            raise MetricsTPUUserError(
                f"config.ladder must be a DegradationLadder, got {type(self._cfg.ladder).__name__}"
            )
        if self._cfg.elastic_min_world < 0:
            raise MetricsTPUUserError(
                f"elastic_min_world must be >= 0, got {self._cfg.elastic_min_world}"
            )
        # windowed semantics (ISSUE 13): the cumulative policy normalizes to
        # None — it IS the engine's default, and keeping it None keeps every
        # pre-window engine's carried state and program keys byte-identical
        win = self._cfg.window
        if win is not None and not isinstance(win, WindowPolicy):
            raise MetricsTPUUserError(
                f"config.window must be a WindowPolicy, got {type(win).__name__}"
            )
        self._window = win if (win is not None and win.kind != "cumulative") else None
        if self._window is not None:
            if self._cfg.mesh is not None and not self._deferred:
                raise MetricsTPUUserError(
                    "windowed serving on a mesh requires mesh_sync='deferred': "
                    "a pane rotation is a state-structure operation with no "
                    "per-step delta form for the step-sync merge"
                )
            reason = self._window.unsupported_reason(
                metric, mesh_deferred=self._deferred
            )
            if reason is not None:
                raise MetricsTPUUserError(
                    f"metric cannot serve under WindowPolicy "
                    f"{self._window.fingerprint()!r}: {reason}"
                )
        # carried state gains the pane axis only for stacked (tumbling/
        # sliding) rings OFF the stream-sharded path — under stream_shard the
        # pane extends the pager's local stream coordinate instead, so cold
        # panes spill through the existing compressed pager
        self._win_stacked = (
            self._window is not None
            and self._window.stacked
            and not getattr(self, "_stream_shard", False)
        )
        self._panes = self._window.panes if self._window is not None else 1
        self._pane_cursor = 0
        self._rotations = 0
        self._last_rotate_batches = 0
        # replay cursor at which the OPEN pane started — the pane-fill
        # observable (snapshot provenance) and the empty-pane guard for
        # drift: a time-cadence catch-up closes panes no batch ever touched
        self._pane_open_cursor = 0
        self._win_clock = (
            self._window.time_source() if self._window is not None else time.monotonic
        )
        self._last_rotate_time = self._win_clock() if self._window is not None else 0.0
        self._drift = self._cfg.drift
        if self._drift is not None:
            if not isinstance(self._drift, DriftDetector):
                raise MetricsTPUUserError(
                    f"config.drift must be a DriftDetector, got {type(self._drift).__name__}"
                )
            if self._window is None:
                raise MetricsTPUUserError(
                    "config.drift needs a rotating config.window: drift alarms "
                    "evaluate per CLOSED PANE — without rotations there is "
                    "nothing to record (use DriftDetector standalone otherwise)"
                )
            if getattr(self, "_stream_shard", False):
                raise MetricsTPUUserError(
                    "automatic drift evaluation is not supported under "
                    "stream_shard=True (a per-rotation all-streams read would "
                    "fault every cold pane back in); record per-stream pane "
                    "results into a standalone DriftDetector instead"
                )
            if self._drift.raise_on_alarm:
                raise MetricsTPUUserError(
                    "config.drift must not set raise_on_alarm: the detector "
                    "records on the DISPATCHER thread, where a raised alarm "
                    "would become the sticky dispatcher error and take serving "
                    "down — alarms surface as drift_alarm trace events and "
                    "counters; poll detector.alarms() (raise_on_alarm is for "
                    "standalone use)"
                )
        # ISSUE 11 self-defense layer: None (the default) keeps the hot path
        # at one `is not None` check per site, matching the trace contract
        self._admission = self._cfg.admission
        self._ladder = self._cfg.ladder
        if self._ladder is not None:
            # a DegradationLadder is STATEFUL per engine (level, streaks, and
            # the engine-side rung effects it drives): two engines advancing
            # one ladder would each engage/release disjoint rung subsets and
            # leave rungs stuck — refuse the rebind. (An AdmissionPolicy MAY
            # be shared: that is a shared admission domain, by design.)
            import weakref

            owner = getattr(self._ladder, "_owner", None)
            if owner is not None and owner() is not None and owner() is not self:
                raise MetricsTPUUserError(
                    "this DegradationLadder is already driving another engine; "
                    "a ladder is stateful per engine — construct one per engine "
                    "(share the AdmissionPolicy for a shared admission domain)"
                )
            self._ladder._owner = weakref.ref(self)
        # serializes ladder state + rung application: ticks come from the
        # dispatcher (per group) AND from producers on shed rejections
        self._ladder_lock = threading.Lock()
        self._ladder_marks = (0, 0)  # (steps, page_outs) at the last tick
        self._ladder_ticks = 0
        self._ladder_p99: Optional[float] = None  # throttled-memoized signal
        self._ladder_saved_window = self._cfg.coalesce_window_ms
        self._ladder_quantized = False
        self._defer_cold_reads = False
        self._result_cache: Dict[Any, Any] = {}
        # submit-time enqueue stamps by object identity (ALWAYS on — a dict
        # set/pop per submitted batch, dwarfed by the queue op itself): the
        # oldest-item age BackpressureTimeout reports, and the residency
        # floor recovery diagnostics start from
        self._submit_stamps: Dict[int, float] = {}
        # the flight recorder: None (the default) means every site below is
        # one attribute load + None check — the whole disabled-path cost
        self._trace = self._cfg.trace
        # submit-time [trace id, submit stamp] pairs for queued items, keyed
        # by object identity — registered BEFORE enqueue (the dispatcher may
        # process an item the instant it lands) and popped when its group is
        # picked up; entries live exactly as long as their item is queued,
        # so ids never alias
        self._trace_ids: Dict[int, List[Any]] = {}
        self._group_tid: Optional[str] = None  # dispatcher-thread current group
        self._last_aot_outcome = "hit"  # set by every _update_program call
        divisor = 1
        if self._cfg.mesh is not None:
            divisor = int(np.prod([self._cfg.mesh.shape[a] for a in self._axis_names()]))
        self._world = divisor  # shards carrying local state under deferred sync
        self._policy = BucketPolicy(self._cfg.buckets, pad_value=self._cfg.pad_value, divisor=divisor)
        self._aot = aot_cache if aot_cache is not None else AotCache(self._cfg.compilation_cache_dir)
        self._stats = EngineStats(self._cfg.telemetry_capacity)
        if self._window is not None:
            self._stats.window_policy = self._window.fingerprint()
            self._stats.live_panes = 1
        self._metric_fp = metric_fingerprint(metric)
        if self._cfg.snapshot_every > 0 and not self._cfg.snapshot_dir:
            raise MetricsTPUUserError("snapshot_every > 0 requires snapshot_dir")
        # the quantized-sync policy tag (metric.py::sync_precision_tag) —
        # pinned at construction and folded into EVERY program key: set the
        # policy BEFORE building the engine (like the kernel backend, a
        # post-hoc change would hand stale executables the wrong bundle)
        self._precision_tag = getattr(metric, "sync_precision_tag", lambda: "exact")()
        self._compress = bool(self._cfg.compress_payloads)
        self._payload_split: Optional[Tuple[int, int]] = None
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, self._cfg.max_queue))
        self._program_memo: Dict[Tuple, Any] = {}
        # guards every read-modify-write of self._state against the
        # dispatcher's step loop (which DONATES the live buffers): reset /
        # restore / per-stream resets / state reads are atomic w.r.t. steps.
        # RLock because _process_group's snapshot cadence re-enters
        # _save_snapshot under the same lock.
        self._state_lock = threading.RLock()
        self._inflight: "deque" = deque()
        self._worker: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._step = 0
        self._batches_done = 0
        # host-topology provenance (ISSUE 15): a fleet-managed engine is one
        # HOST of an H-process SPMD fleet (engine/fleet/) — the FleetEngine
        # stamps these so every snapshot carries (num_hosts, process_id) and
        # the restore matrix can refuse cross-topology commits loudly. The
        # defaults (1, 0) ARE the single-process topology, so pre-fleet
        # snapshots (no host fields in meta) restore unchanged.
        self._fleet_hosts = 1
        self._fleet_pid = 0
        self._fleet_cut: Optional[int] = None  # stamped per fleet snapshot cut
        self._fleet_plan_cursor = 0  # global-plan position at the stamped cut
        # fleet-driven pane rotation (ISSUE 20): the FleetEngine sets this so
        # the LOCAL batch cadence goes quiet — a fleet host's _batches_done
        # counts only OWNED plan batches, so per-host cadence would rotate at
        # host-dependent positions; the fleet drives rotate_pane() from the
        # shared global plan cursor instead (every host rotates at the same
        # plan-agreed boundary, no clock, no collective)
        self._fleet_rotation = False
        # the layout always describes ONE pane's packing (kind tree): ring
        # windows stack (panes, n) buffers of these rows, and the per-row
        # plan is what pack_stacked/unpack_stacked apply slot-wise
        self._layout: Optional[ArenaLayout] = (
            ArenaLayout.for_state(self._kind_abstract_state_tree())
            if self._cfg.use_arena
            else None
        )
        # metrics that DERIVE compute attrs from data (Accuracy's input-mode
        # latch) must latch before any program key is built — see
        # _latch_host_attrs. No declared attrs (the common case) = no cost.
        self._needs_attr_latch = any(
            v is None for v in metric.host_compute_attrs().values()
        )
        # PIN the kernel backend at construction: config wins; None inherits
        # whatever selection is ambient HERE (use_backend ctx > process
        # default > env > auto). Pinning — not re-reading per build — is what
        # keeps one engine's programs coherent: update programs build on the
        # dispatcher THREAD and compute programs on the caller's, so a
        # thread-local context active at result() time must not hand the two
        # different lowerings. A bad name fails construction, not the
        # dispatcher thread.
        self._kernel_backend = (
            self._cfg.kernel_backend if self._cfg.kernel_backend is not None else current_backend()
        )
        resolve_backend(self._kernel_backend)
        # whole-step megakernel plan (ISSUE 16): static, judged ONCE here.
        # Engine-level ineligibility (no arena / replicated mesh / stacked
        # multistream layout — _megastep_unsupported_reason) falls back to
        # the per-leaf kernels silently under "megastep" but RAISES under
        # "megastep_interpret": the interpret tier exists for parity tests,
        # and a test that silently ran the per-leaf path would be testing
        # the wrong program. Per-DTYPE ineligibility degrades per leaf under
        # BOTH (that degradation is the megastep contract, not an error);
        # every fallback verdict lands in stats.kernel_fallbacks.
        self._megastep_plan = None
        self._megastep_reason: Optional[str] = None
        if self._kernel_tag() in MEGASTEP_BACKENDS:
            self._megastep_reason = self._megastep_unsupported_reason()
            if self._megastep_reason is not None:
                if self._kernel_tag() == "megastep_interpret":
                    raise MetricsTPUUserError(
                        f"kernel_backend='megastep_interpret' but this engine "
                        f"cannot take the whole-step path: {self._megastep_reason} "
                        f"(use 'megastep' for silent per-leaf fallback, or "
                        f"'pallas_interpret' to test the per-leaf kernels)"
                    )
                self._stats.record_kernel_fallback(f"engine:{self._megastep_reason}")
            else:
                from metrics_tpu.engine.megastep import MegastepPlan

                self._megastep_plan = MegastepPlan(metric, self._layout)
                for key, why in sorted(self._megastep_fallback_reasons().items()):
                    self._stats.record_kernel_fallback(f"dtype.{key}:{why}")
        self._merged_abs_memo: Optional[Any] = None
        # boundary-merge memo: (state_version, merged) — repeat reads between
        # updates (result() polls over S streams, state() after result())
        # reuse one merge instead of paying a collective bundle each
        self._state_version = 0
        self._merged_memo: Optional[Tuple[int, Any]] = None
        self._state = self._put_state(self._init_state_tree())
        self._donate = bool(self._cfg.donate) and jax.default_backend() != "cpu"
        # transactional steps: None auto-enables when the shadow is FREE
        # (donation off — the step inputs survive the call untouched), when a
        # chaos injector is present, or when the WATCHDOG is armed — its
        # whole contract is rollback-and-retry on expiry, which without a
        # shadow under donation would silently degrade to sticky-with-torn-
        # state. With donation on, the shadow is one device copy per step
        # (documented cost).
        self._transactional = (
            self._cfg.transactional
            if self._cfg.transactional is not None
            else (
                (not self._donate)
                or inj is not None
                or self._cfg.step_timeout_s > 0
            )
        )
        # jittered-backoff stream, seeded so chaos runs replay exactly
        self._retry_rng = np.random.RandomState(
            ((inj.seed if inj is not None else 0) ^ 0x5EED) & 0x7FFFFFFF
        )
        # dead-letter ledger for screened-out batches: newest records kept
        # (payload included) up to the cap; lifetime counts live in stats
        self._quarantine: "deque[QuarantineRecord]" = deque(
            maxlen=max(1, int(self._cfg.quarantine_capacity))
        )
        # the watchdog arms when configured OR when the chaos plan can fire
        # the watchdog site (so the injected expiry exercises the real path)
        self._watchdog_enabled = self._cfg.step_timeout_s > 0 or (
            inj is not None and inj.has_site("watchdog")
        )
        # deferred steady steps carry ZERO collectives, so the CPU in-process
        # communicator hazard doesn't apply — only step-sync CPU meshes
        # serialize; boundary merges block under the state lock in both modes
        self._serialize = (
            self._cfg.mesh is not None
            and self._cfg.mesh.devices.flat[0].platform == "cpu"
            and not self._deferred
        )
        self._stats.mesh_sync = (
            None if self._cfg.mesh is None else ("deferred" if self._deferred else "step")
        )

    # -------------------------------------------------------------- capability checks

    def _update_path_unsupported_reason(self, metric: Any) -> Optional[str]:
        """The engine-kind-specific update capability (subclasses reroute:
        multi-stream needs the segmented path). Mesh-mode checks stay in
        :meth:`_serving_unsupported_reason` so every engine kind gets them.

        Group-keyed metrics (retrieval, detection MAP —
        ``masked_update_strategy() == "grouped"``) refuse HERE with a typed
        pointer at :class:`metrics_tpu.engine.ragged.RaggedEngine`: their
        cat-list states are the ragged path's job, and the old generic
        delta/scan message was a dead end (ISSUE 17)."""
        return metric.masked_update_unsupported_reason()

    def _megastep_unsupported_reason(self) -> Optional[str]:
        """Why this ENGINE cannot take the whole-step megakernel path at all
        (None = it can; per-dtype degradation is judged separately by the
        plan). The base engine needs the packed arena as its carried form and
        a single-device program — the replicated-mesh step bodies
        (``sharded_local_step``/``sharded_masked_step``) own their pack/unpack
        structure and keep the per-leaf kernels. Subclasses reroute
        (multi-stream: stream-sharded engines take the SEGMENT form instead,
        stacked ones cannot)."""
        if self._layout is None:
            return "no_arena"
        if self._cfg.mesh is not None:
            return "mesh"
        return None

    def _megastep_fallback_reasons(self) -> Dict[str, str]:
        """Per-dtype degradation verdicts for THIS engine's megastep form
        (the stream-sharded override consults the segment form's tighter
        VMEM bound)."""
        return self._megastep_plan.fallback_reasons() if self._megastep_plan else {}

    def _serving_unsupported_reason(self, metric: Any) -> Optional[str]:
        reason = self._update_path_unsupported_reason(metric)
        if reason is not None:
            return reason
        if self._cfg is not None and self._cfg.mesh is not None:
            if self._cfg.mesh_sync == "deferred":
                # deferred mode needs no per-step delta merge — any masked
                # strategy (delta/custom/scan) runs shard-locally — but the
                # BOUNDARY merge folds whole states by their dist_reduce_fx,
                # so every state must have a canonical stacked merge
                r = (
                    metric.stacked_merge_unsupported_reason()
                    if hasattr(metric, "stacked_merge_unsupported_reason")
                    else None
                )
                if r is not None:
                    return f"deferred-sync mesh serving needs dist_reduce_fx-mergeable states: {r}"
            else:
                r = _mesh_step_unsupported_reason(metric)
                if r is not None:
                    return r
        return None

    # ------------------------------------------------------------------ mesh helpers

    def _axis_names(self) -> Tuple[str, ...]:
        a = self._cfg.axis
        return tuple(a) if isinstance(a, (tuple, list)) else (a,)

    def _replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self._cfg.mesh, P())

    def _batch_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self._cfg.mesh, P(self._cfg.axis))

    # ----------------------------------------------------------------- state plumbing

    def _kind_init_state_tree(self) -> Any:
        """One PANE's fresh logical state (the engine-kind hook — the
        multi-stream engine stream-stacks here; the window layer stacks the
        pane axis on top in :meth:`_init_state_tree`)."""
        return self._metric.init_state()

    def _kind_abstract_state_tree(self) -> Any:
        """One pane's ``ShapeDtypeStruct`` tree (engine-kind hook) — also the
        :class:`ArenaLayout` template: the layout always describes ONE pane's
        packing, and windowed engines stack rings of those rows."""
        return self._metric.abstract_state()

    def _init_state_tree(self) -> Any:
        """Fresh logical (UNPACKED) state pytree — pane-stacked (every leaf
        gains a leading ``panes`` axis of identical init rows) for ring
        windows; the engine-kind tree otherwise."""
        tree = self._kind_init_state_tree()
        if not self._win_stacked:
            return tree
        return jax.tree.map(
            lambda x: jnp.tile(jnp.asarray(x)[None], (self._panes,) + (1,) * jnp.ndim(x)),
            tree,
        )

    def _abstract_state_tree(self) -> Any:
        """``ShapeDtypeStruct`` pytree of the logical CARRIED state (no
        sharding) — pane-stacked under ring windows."""
        tree = self._kind_abstract_state_tree()
        if not self._win_stacked:
            return tree
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct((self._panes,) + tuple(s.shape), s.dtype),
            tree,
        )

    def _pack(self, tree: Any) -> Any:
        if self._layout is None:
            return tree
        return (
            self._layout.pack_stacked(tree) if self._win_stacked else self._layout.pack(tree)
        )

    def _unpack(self, carried: Any) -> Any:
        if self._layout is None:
            return carried
        return (
            self._layout.unpack_stacked(carried)
            if self._win_stacked
            else self._layout.unpack(carried)
        )

    def _stack_shards(self, tree: Any) -> Any:
        """Logical state tree -> shard-stacked tree: every leaf gains a
        leading ``world`` axis, each row an identical copy (every shard starts
        its local accumulation from the metric's defaults — the reference's
        per-process semantics)."""
        return jax.tree.map(
            lambda x: jnp.tile(jnp.asarray(x)[None], (self._world,) + (1,) * jnp.ndim(x)),
            tree,
        )

    def _shard0_stack(self, tree: Any) -> Any:
        """Logical state tree -> shard-stacked tree with the WHOLE state in
        shard 0 and the identity (init) state everywhere else — the exact
        deferred embedding of a global state: the boundary merge folds the
        identity rows away (sum+0, min/max vs identity, cat of invalid-marked
        buffers), so compute recovers the embedded state unchanged. Used when
        restoring a single-device/step-sync snapshot into a deferred engine."""
        init = self._init_state_tree()

        def one(s: Any, i: Any) -> Any:
            s = jnp.asarray(s)
            if self._world == 1:
                return s[None]
            rest = jnp.tile(jnp.asarray(i, s.dtype)[None], (self._world - 1,) + (1,) * s.ndim)
            return jnp.concatenate([s[None], rest], axis=0)

        return jax.tree.map(one, tree, init)

    def _shard_sharding(self):
        """Dim-0-sharded (shard-local) placement for deferred carried state."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self._cfg.mesh, P(self._cfg.axis))

    def _put_state(self, state: Any, packed: bool = False, stacked: bool = False) -> Any:
        """Device-commit a state. ``state`` is the logical pytree unless
        ``packed``/``stacked`` say it is already in the carried form. Step
        mode replicates over the mesh; deferred mode stacks every leaf over a
        leading shard axis (``stacked=False`` tiles the logical state to every
        shard) and shards dim 0 over the mesh axis — each device owns exactly
        its local state."""
        if self._deferred:
            if not stacked:
                state = self._stack_shards(jax.tree.map(jnp.asarray, state))
                packed = False
            if not packed and self._layout is not None:
                # windowed deferred states carry TWO leading stack axes
                # (world, panes) ahead of each pane row's flat form
                state = self._layout.pack_stacked(
                    state, lead=2 if self._win_stacked else 1
                )
            sh = self._shard_sharding()
            return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), sh), state)
        if not packed:
            state = self._pack(jax.tree.map(jnp.asarray, state))
        if self._cfg.mesh is None:
            return jax.tree.map(jnp.asarray, state)
        rep = self._replicated_sharding()
        return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), rep), state)

    def _abstract_state(self) -> Any:
        """The CARRIED state's lowering template: packed arena (or logical
        pytree) — replicated under a step-sync mesh, shard-stacked and dim-0
        sharded under deferred sync."""
        if self._deferred:
            if self._layout is not None:
                abs_state = (
                    self._layout.abstract_stream_stacked(self._world, self._panes)
                    if self._win_stacked
                    else self._layout.abstract_stacked(self._world)
                )
            else:
                abs_state = jax.tree.map(
                    lambda s: jax.ShapeDtypeStruct((self._world,) + tuple(s.shape), s.dtype),
                    self._abstract_state_tree(),
                )
            sh = self._shard_sharding()
            return jax.tree.map(
                lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh), abs_state
            )
        if self._layout is not None:
            abs_state = (
                self._layout.abstract_paned(self._panes)
                if self._win_stacked
                else self._layout.abstract()
            )
        else:
            abs_state = self._abstract_state_tree()
        if self._cfg.mesh is None:
            return abs_state
        rep = self._replicated_sharding()
        return jax.tree.map(lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=rep), abs_state)

    def _merged_abstract(self) -> Any:
        """Shape/dtype template of the deferred boundary merge's output — the
        GLOBAL logical state (``cat`` buffers concatenated across shards).
        Derived from ``Metric.merge_stacked_states``, whose output layout
        matches the on-device ``sync_states`` merge exactly."""
        if self._merged_abs_memo is None:
            stacked_abs = jax.tree.map(
                lambda s: jax.ShapeDtypeStruct((self._world,) + tuple(s.shape), s.dtype),
                self._abstract_state_tree(),
            )
            self._merged_abs_memo = jax.eval_shape(self._metric.merge_stacked_states, stacked_abs)
        return self._merged_abs_memo

    # ------------------------------------------------------------------ AOT programs

    def _update_program(self, payload: Any, mask: np.ndarray):
        """The compiled step for this payload signature (AOT, cached).

        Hot path: a per-engine memo keyed by the concrete payload signature
        (one tree_flatten) skips the abstract-tree construction and the full
        structural program key on every steady-state step.
        """
        memo_key = (AotCache.signature_of(payload), mask.shape)
        prog = self._program_memo.get(memo_key)
        if prog is not None:
            self._aot.count_hit()  # memo short-circuit still counts as a cache hit
            self._last_aot_outcome = "hit"
            return prog
        payload_abs = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
            if isinstance(x, (np.ndarray, jnp.ndarray))
            else x,
            payload,
        )
        mask_abs = jax.ShapeDtypeStruct(mask.shape, np.dtype(bool))
        # the CARRIED-state template is part of the program's identity: two
        # engines sharing a cache but differing in use_arena (or stream
        # count) take different state pytrees through the same payload
        # signature — omitting it hands one the other's executable. The
        # resolved KERNEL backend is part of it too (the lowering differs):
        # a pallas engine and an xla engine sharing a cache must not
        # exchange executables.
        key = self._aot.program_key(
            f"{self._update_kind()}+k.{self._kernel_tag()}", self._metric_fp,
            arg_tree=(self._abstract_state(), payload_abs, mask_abs),
            mesh=self._cfg.mesh, donate=self._donate, sync=self._sync_tag(),
            precision=self._precision_tag,
        )
        # attribution BEFORE the lookup: whether THIS call compiles. (The
        # benign race — another engine inserting the identical key in the
        # gap — mislabels a shared-key duel, never pollutes across keys the
        # way a shared miss-counter delta would.)
        self._last_aot_outcome = "hit" if self._aot.contains(key) else "miss"
        prog = self._aot.get_or_compile(
            key, lambda: self._build_update_program(payload_abs, mask_abs)
        )
        self._program_memo[memo_key] = prog
        return prog

    def _update_kind(self) -> str:
        return "update"

    def _sync_tag(self) -> str:
        """The mesh sync mode every program key carries: step-sync and
        deferred engines lower DIFFERENT programs over identical payload
        signatures (in-step collectives vs none; replicated vs shard-local
        state), so engines in different modes sharing an ``AotCache`` must
        never exchange executables."""
        return "deferred" if self._deferred else "step"

    def _kernel_tag(self) -> str:
        """The RESOLVED kernel backend this engine's programs lower with —
        folded into every program key. Derived from the CONSTRUCTION-pinned
        selection, never from the build-time ambient context."""
        return resolve_backend(self._kernel_backend)

    def _kernel_scope(self):
        """Trace-time kernel-backend override for program builds: always
        pushes the pinned selection, so an ambient ``use_backend`` on the
        building thread cannot leak into this engine's programs (and the
        build never leaks into user traces — the override is thread-local
        and scoped)."""
        return use_backend(self._kernel_backend)

    def _traced_update(self, state_tree: Any, payload: Any, mask: Any) -> Any:
        """The step body on the LOGICAL state tree (inside jit). Subclasses
        reroute this (multi-stream segmented updates)."""
        a, kw = payload
        return self._metric.update_state_masked(state_tree, *a, mask=mask, **kw)

    def _step_update(self, state_tree: Any, payload: Any, mask: Any) -> Any:
        """The window-aware step body: on a ring window, ``payload`` leads
        with the RUNTIME pane index (a 0-d int32 the dispatcher prepends in
        :meth:`_run_padded_step`), the current pane row is dynamically
        indexed out of the pane-stacked tree, updated by the engine-kind
        update, and dynamically written back — one slice + one update per
        leaf, both runtime-indexed, so a rotation changes an ARGUMENT, never
        the trace (the zero-steady-compile contract of ISSUE 13)."""
        if not self._win_stacked:
            return self._traced_update(state_tree, payload, mask)
        from jax import lax

        a, kw = payload
        pane, rest = a[0], tuple(a[1:])
        row = jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(x, pane, 0, keepdims=False), state_tree
        )
        new_row = self._traced_update(row, (rest, kw), mask)
        return jax.tree.map(
            lambda x, r: lax.dynamic_update_index_in_dim(x, r, pane, 0),
            state_tree,
            new_row,
        )

    def _step_callable(self, payload_abs: Any, mask_abs: Any):
        """The pure ``(state, payload, mask) -> (new_state, token)`` step body
        for one payload signature — a FRESH closure per call (so two builds
        under different kernel backends can never share a trace-cache entry).
        :meth:`_build_update_program` jits/lowers/compiles it; the program-
        plane analyzer (``metrics_tpu/analysis/program.py``) re-traces it to
        a jaxpr when auditing a built engine's collective/scatter/arena
        invariants. Trace under :meth:`_kernel_scope` either way — kernel
        dispatch is a trace-time decision."""
        mesh = self._cfg.mesh

        if mesh is None:
            plan = self._megastep_plan
            if plan is not None and self._kernel_tag() in MEGASTEP_BACKENDS:
                # whole-step megakernel body: the plan folds the packed delta
                # matrix straight into the arena buffers — the per-leaf
                # unpack → fold → repack intermediates are never traced for
                # eligible dtypes, which is what pins the jaxpr's pallas_call
                # count at O(dtypes) (analysis/rules/pallas.py). The gate
                # re-reads _kernel_tag() so a degrade_kernel demotion
                # (megastep → xla) rebuilds on the per-leaf body naturally.
                # Pane rings index ONE row per dtype buffer around the plan —
                # the same runtime-indexed slice/update discipline as
                # _step_update, applied at the BUFFER level.
                from jax import lax

                win_stacked = self._win_stacked

                def step(state, payload, mask):
                    a, kw = payload
                    if win_stacked:
                        pane, rest = a[0], tuple(a[1:])
                        row = {
                            k: lax.dynamic_index_in_dim(v, pane, 0, keepdims=False)
                            for k, v in state.items()
                        }
                        new_row = plan.apply_masked(row, rest, kw, mask)
                        new_state = {
                            k: lax.dynamic_update_index_in_dim(v, new_row[k], pane, 0)
                            for k, v in state.items()
                        }
                    else:
                        new_state = plan.apply_masked(state, a, kw, mask)
                    return new_state, jnp.sum(mask.astype(jnp.int32))

                return step

            def step(state, payload, mask):
                tree = self._unpack(state)
                new_tree = self._step_update(tree, payload, mask)
                return self._pack(new_tree), jnp.sum(mask.astype(jnp.int32))

            return step

        from metrics_tpu.parallel.embedded import sharded_local_step, sharded_masked_step

        if self._deferred:
            # collective-free shard-local step: each device folds its own rows
            # into its own state row (its own pane ring, under windows); merge
            # happens at explicit boundaries
            return sharded_local_step(
                self._step_update, mesh, self._cfg.axis, payload_abs, mask_abs,
                state_template=self._abstract_state(),
                unpack=self._unpack if self._layout is not None else None,
                pack=self._pack if self._layout is not None else None,
            )
        return sharded_masked_step(
            self._metric, mesh, self._cfg.axis, payload_abs, mask_abs, layout=self._layout
        )

    def _build_update_program(self, payload_abs: Any, mask_abs: Any):
        """Compile ``(state, payload, mask) -> (new_state, token)``.

        ``state`` is the carried form — the packed per-dtype arena by default;
        the body unpacks it with static slices, runs the masked update, and
        repacks (both ends fuse away). ``token`` is the step's global
        valid-row count — a tiny NON-donated output the dispatcher can block
        on to bound in-flight depth (the state itself may already have been
        donated into the NEXT step by the time the dispatcher needs to wait,
        and a donated buffer cannot be synced on). It doubles as a liveness
        cross-check in telemetry.
        """
        step = self._step_callable(payload_abs, mask_abs)
        jitted = jax.jit(step, donate_argnums=(0,) if self._donate else ())
        if self._cfg.mesh is None:
            with self._kernel_scope():  # kernel dispatch happens at trace time
                return jitted.lower(self._abstract_state(), payload_abs, mask_abs).compile()
        n_rows = mask_abs.shape[0]
        batch_sh = self._batch_sharding()
        rep_sh = self._replicated_sharding()
        mask_sharded = jax.ShapeDtypeStruct(mask_abs.shape, mask_abs.dtype, sharding=batch_sh)
        payload_abs = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(
                s.shape, s.dtype,
                sharding=batch_sh if is_batch_leaf(s, n_rows) else rep_sh,
            )
            if hasattr(s, "shape")
            else s,
            payload_abs,
        )
        with self._kernel_scope():
            return jitted.lower(self._abstract_state(), payload_abs, mask_sharded).compile()

    def _compute_input_abstract(self) -> Any:
        """What the compute program takes: the carried state (step mode) or
        the boundary merge's replicated GLOBAL state (deferred mode)."""
        if not self._deferred:
            return self._abstract_state()
        rep = self._replicated_sharding()
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=rep),
            self._merged_abstract(),
        )

    def _compute_tree(self, state: Any) -> Any:
        """Trace-time view of the compute input as the LOGICAL state tree
        (merged deferred states arrive already logical; carried states
        unpack from the arena). Pane-stacked under ring windows — the window
        FOLD (:meth:`_window_fold_traced`) is a separate step so per-pane
        readers can skip it."""
        return state if self._deferred else self._unpack(state)

    # ------------------------------------------------------------ window plumbing

    def _window_tag(self) -> str:
        """The window policy component of program-key kind strings: two
        policies over identical state signatures lower DIFFERENT fold/rotate
        programs (tumbling indexes, sliding merges, ewma scales), so the
        policy is part of every window-sensitive key."""
        return self._window.fingerprint() if self._window is not None else "none"

    def _window_fold_traced(self, tree: Any, *extra: Any) -> Any:
        """Fold a pane-stacked logical tree to the window's RESULT view
        (inside jit): sliding merges every live pane via
        ``merge_stacked_states`` (sum/min/max elementwise, cat buffers
        concatenated across panes — per-pane capacity buffers fold exactly);
        tumbling dynamically indexes the current pane (``extra[0]``, a
        runtime scalar — P cursor positions share ONE compiled program);
        unstacked engines pass through."""
        if not self._win_stacked:
            return tree
        if self._window.kind == "sliding":
            return self._metric.merge_stacked_states(tree)
        from jax import lax

        pane = extra[0]
        return jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(x, pane, 0, keepdims=False), tree
        )

    def _compute_extra_abs(self) -> Tuple[Any, ...]:
        """Abstract extra compute-program arguments the window fold needs
        (the runtime pane cursor for tumbling rings; nothing otherwise)."""
        if self._win_stacked and self._window.kind == "tumbling":
            return (jax.ShapeDtypeStruct((), jnp.int32),)
        return ()

    def _compute_extra(self) -> Tuple[Any, ...]:
        """Concrete extra compute-program arguments at call time."""
        if self._win_stacked and self._window.kind == "tumbling":
            return (jnp.asarray(self._pane_cursor, jnp.int32),)
        return ()

    def _compute_program(self):
        # compute programs carry the kernel tag too: functional compute code
        # can route through the dispatcher (e.g. the bincount family). The
        # WINDOW tag is part of the kind: tumbling and sliding folds lower
        # different programs over identical state signatures.
        key = self._aot.program_key(
            f"compute+k.{self._kernel_tag()}+w.{self._window_tag()}", self._metric_fp,
            arg_tree=(self._compute_input_abstract(),) + self._compute_extra_abs(),
            mesh=self._cfg.mesh, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )
        metric = self._metric

        def build():
            def compute(state, *extra):
                tree = self._window_fold_traced(self._compute_tree(state), *extra)
                return metric.compute_from(tree)

            with self._kernel_scope():
                return (
                    jax.jit(compute)
                    .lower(self._compute_input_abstract(), *self._compute_extra_abs())
                    .compile()
                )

        return self._aot.get_or_compile(key, build)

    def _pane_value_program(self):
        """ONE pane's result from the carried/merged state + a runtime pane
        index — the drift detector's per-closing-pane observable. For
        tumbling rings this IS the compute program (same signature, same
        fold); sliding rings compile one extra indexed-pane program (cached,
        so rotations stay compile-free after the first)."""
        if self._window.kind == "tumbling":
            return self._compute_program()
        pane_abs = jax.ShapeDtypeStruct((), jnp.int32)
        key = self._aot.program_key(
            f"pane_value+k.{self._kernel_tag()}+w.{self._window_tag()}", self._metric_fp,
            arg_tree=(self._compute_input_abstract(), pane_abs),
            mesh=self._cfg.mesh, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )
        metric = self._metric

        def build():
            from jax import lax

            def pane_value(state, pane):
                tree = self._compute_tree(state)
                row = jax.tree.map(
                    lambda x: lax.dynamic_index_in_dim(x, pane, 0, keepdims=False), tree
                )
                return metric.compute_from(row)

            with self._kernel_scope():
                return (
                    jax.jit(pane_value)
                    .lower(self._compute_input_abstract(), pane_abs)
                    .compile()
                )

        return self._aot.get_or_compile(key, build)

    def _rotate_program(self):
        """The compiled ring-rotation init-fill: ``(state, pane) -> state``
        with the INCOMING pane row reset to the metric's init state — one
        runtime-indexed write per dtype buffer (or per leaf without arenas),
        non-donated (the plan/commit split: a retried transient re-runs
        against the untouched carry). One compile per engine, ever."""
        pane_abs = jax.ShapeDtypeStruct((), jnp.int32)
        key = self._aot.program_key(
            f"pane_rotate+k.{self._kernel_tag()}+w.{self._window_tag()}", self._metric_fp,
            arg_tree=(self._abstract_state(), pane_abs),
            mesh=self._cfg.mesh, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )

        def build():
            init_tree = jax.tree.map(jnp.asarray, self._kind_init_state_tree())
            if self._layout is not None:
                init_row = {
                    k: np.asarray(v) for k, v in self._layout.pack(init_tree).items()
                }

                def rotate(state, pane):
                    # pane axis is ndim-2 in both carried forms ((panes, n)
                    # and (world, panes, n)); .at with a traced index lowers
                    # to one dynamic-update per dtype — never per leaf
                    out = {}
                    for k, v in state.items():
                        row = jnp.asarray(init_row[k])
                        if v.ndim == 3:  # (world, panes, n): broadcast over shards
                            out[k] = v.at[:, pane, :].set(row)
                        else:
                            out[k] = v.at[pane, :].set(row)
                    return out
            else:
                def rotate(state, pane):
                    def one(x, i):
                        i = jnp.asarray(i, x.dtype)
                        if self._deferred:  # (world, panes) + leaf shape
                            return x.at[:, pane].set(i)
                        return x.at[pane].set(i)

                    return jax.tree.map(one, state, init_tree)

            with self._kernel_scope():
                return jax.jit(rotate).lower(self._abstract_state(), pane_abs).compile()

        return self._aot.get_or_compile(key, build)

    def _decay_program(self):
        """The compiled EWMA rotation: one fused scale-accumulate over the
        carried per-dtype buffers (eligibility guarantees every state is a
        float sum accumulator, so the scalar multiply IS the exact decay of
        the accumulation). Non-donated, same plan/commit contract as the
        ring rotation."""
        key = self._aot.program_key(
            f"pane_decay+k.{self._kernel_tag()}+w.{self._window_tag()}", self._metric_fp,
            arg_tree=self._abstract_state(),
            mesh=self._cfg.mesh, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )
        decay = self._window.decay

        def build():
            def scale(state):
                return jax.tree.map(lambda x: x * jnp.asarray(decay, x.dtype), state)

            with self._kernel_scope():
                return jax.jit(scale).lower(self._abstract_state()).compile()

        return self._aot.get_or_compile(key, build)

    def _merge_program(self):
        """The deferred boundary merge: shard-local carried state -> replicated
        global logical state, one fused collective bundle
        (``parallel/embedded.py::sharded_state_merge``). Cached like every
        other program; compiled lazily at the first boundary."""
        key = self._aot.program_key(
            f"merge+k.{self._kernel_tag()}", self._metric_fp,
            arg_tree=self._abstract_state(),
            mesh=self._cfg.mesh, donate=False, sync=self._sync_tag(),
            precision=self._precision_tag,
        )

        def build():
            merge = self._merge_callable()
            with self._kernel_scope():
                return jax.jit(merge).lower(self._abstract_state()).compile()

        return self._aot.get_or_compile(key, build)

    def _merge_callable(self):
        """The deferred boundary merge body (un-jitted) — shared by the
        program build and the program-plane analyzer, which re-traces it to
        audit the quantized-sync policy against the actual bundle."""
        from metrics_tpu.parallel.embedded import sharded_state_merge

        return sharded_state_merge(
            self._metric, self._cfg.mesh, self._cfg.axis,
            state_template=self._abstract_state(),
            unpack=self._unpack if self._layout is not None else None,
        )

    def _payload_leaf_info(self) -> Optional[Any]:
        """The ``(fx, leaf, precision)`` triples one fused sync of THIS
        engine's carried state moves (subclasses rescale: the unsharded
        multistream engine syncs the (S, ...)-stacked state)."""
        info_fn = getattr(self._metric, "sync_leaf_info", None)
        return info_fn() if info_fn is not None else None

    def _fleet_leaf_info(self) -> Optional[Any]:
        """The ``(fx, leaf, precision)`` triples ONE HOST's logical state
        contributes to the FLEET boundary fold — shaped like what
        ``state()`` returns, which is what the fleet stacks and folds.
        Pane-stacked ring engines scale by the pane count (the fold moves
        the whole ring); the stream-sharded engine overrides with its
        (panes, S)-scaled form (its per-mesh accounting stays unscaled —
        the routed step never syncs the stacked state)."""
        info = self._payload_leaf_info()
        if not info or not self._win_stacked:
            return info
        return [
            (fx, jax.ShapeDtypeStruct((self._panes,) + tuple(leaf.shape), leaf.dtype), prec)
            for fx, leaf, prec in info
        ]

    def _payload_split_for(self, world: int, leaf_info: Any = None) -> Tuple[int, int]:
        """(exact_bytes, quantized_bytes) one participant contributes to a
        fused sync of this engine's carried state over a ``world``-wide axis
        — THE payload-accounting formula, shared by the per-engine memoized
        :meth:`_sync_payload_split` (world = the mesh) and the fleet's
        boundary accounting (world = the host count, ``leaf_info`` = the
        host-logical :meth:`_fleet_leaf_info`), so the split convention can
        never diverge between the two surfaces."""
        info = leaf_info if leaf_info is not None else self._payload_leaf_info()
        if not info:
            return (0, 0)
        from metrics_tpu.parallel.collectives import (
            fused_sync_plan,
            sync_payload_bytes,
        )

        total = sync_payload_bytes(info, world)
        quant = 4 * fused_sync_plan(info, world)["q8_words"]
        return (total - quant, quant)

    def _sync_payload_split(self) -> Tuple[int, int]:
        """(exact_bytes, quantized_bytes) one fused sync moves per shard
        under the configured policy — the analytic accounting from
        ``parallel/collectives.py::fused_sync_plan``, memoized (the state
        signature is static per engine). Feeds the OpenMetrics
        ``sync_payload_bytes{kind=...}`` counters."""
        if self._payload_split is None:
            self._payload_split = self._payload_split_for(self._world)
        return self._payload_split

    def _merged_state(self) -> Any:
        """Run the boundary merge on the carried shard-local state (deferred
        mode; caller holds the state lock). Blocked on before returning: the
        merge bears the collectives, and keeping it serialized under the lock
        is what lets the steady-state pipeline stay async even on CPU meshes.
        Memoized on the state version — reads with no intervening updates
        (polling S streams' results, state() after result()) share ONE merge;
        the merged arrays are ordinary non-donated program outputs, immutable
        and safe to hand out repeatedly."""
        if self._merged_memo is not None and self._merged_memo[0] == self._state_version:
            return self._merged_memo[1]
        program = self._merge_program()  # compile (first boundary) outside the timing

        def merge_once() -> Tuple[Any, float]:
            self._fault("merge")
            t0 = time.perf_counter()
            merged = program(self._state)
            jax.block_until_ready(merged)
            return merged, t0

        # the merge is a non-donated READ of the carried state: any failure
        # leaves the shard-local accumulation fully intact, so transients
        # retry here and everything that escapes still leaves result()/
        # state() serving the last consistent value on the caller's next try
        try:
            merged, t0 = self._retry_transient(merge_once)
        except BaseException as e:  # noqa: BLE001 - typed wrap below
            from metrics_tpu.parallel.embedded import boundary_merge_error

            err = boundary_merge_error(self._cfg.axis, self._world, e)
            if err is e:
                raise
            raise err from e
        merge_us = (time.perf_counter() - t0) * 1e6
        self._stats.record_merge(merge_us)
        self._stats.record_sync_payload(*self._sync_payload_split())
        if self._trace is not None:
            self._trace.complete("merge", trace=ENGINE_TRACE, dur_us=merge_us)
            self._trace.observe("merge_latency_us", merge_us)
        self._merged_memo = (self._state_version, merged)
        return merged

    # --------------------------------------------------------------------- lifecycle

    def start(self) -> "StreamingEngine":
        # also re-arms after a FATAL dispatcher death (the thread exited
        # without draining): once reset()/restore() cleared the sticky error
        # and drained the backlog, the next submit gets a fresh dispatcher
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, name="metrics-tpu-engine", daemon=True
            )
            self._worker.start()
        return self

    def __enter__(self) -> "StreamingEngine":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop()
        if exc_type is None:
            self._raise_if_failed()
        return False

    def stop(self) -> None:
        """Drain the queue and stop the dispatcher (idempotent)."""
        if self._worker is not None:
            # bounded-put loop, not one unconditional put: a DEAD dispatcher
            # (fatal fault) behind a FULL queue has no thread left to read
            # the sentinel, and the liveness check alone races the thread's
            # last instants — re-check between short put attempts so a death
            # mid-stop falls through to the join instead of blocking forever
            while self._worker.is_alive():
                try:
                    self._queue.put(_STOP, timeout=0.1)
                    break
                except queue.Full:
                    continue
            self._worker.join()
            self._worker = None

    def _raise_if_failed(self) -> None:
        if self._error is None:
            return
        # satellite (ISSUE 6): chain the ORIGINAL exception and name the
        # failing batch — cursor (replay coordinate), step, bucket, stream
        # ids — so operators can find the poisoned input from the message
        ctx = getattr(self._error, "_engine_ctx", None) or {}
        detail = "".join(f"; {k}={v}" for k, v in sorted(ctx.items()))
        raise EngineDispatchError(
            f"streaming engine dispatcher failed: "
            f"{type(self._error).__name__}: {self._error}{detail}",
            context=ctx,
        ) from self._error

    # --------------------------------------------------------------------- producers

    def submit(self, *args: Any, timeout: Optional[float] = None, **kwargs: Any) -> None:
        """Enqueue one (ragged) batch. Blocks when the queue is full.

        ``timeout`` (seconds) bounds the wait: when the bounded queue stays
        full for the whole window — the signature of a dead or wedged
        dispatcher behind live producers — the sticky dispatcher error is
        raised if one exists, else :class:`BackpressureTimeout`. ``None``
        (default) keeps the pure-backpressure blocking contract.

        With ``config.admission`` set, the batch must clear the admission
        policy FIRST: a refusal raises the typed
        :class:`~metrics_tpu.engine.admission.AdmissionRejected` (with
        ``retry_after_s``) before anything queues — a rejected batch never
        consumes a replay cursor."""
        self._raise_if_failed()
        self.start()
        if self._admission is not None:
            self._admitted_submit(None, (args, kwargs), (args, kwargs), timeout)
        else:
            self._submit_item((args, kwargs), timeout)

    def _admitted_submit(
        self, stream_id: Optional[int], item: Any, payload: Any,
        timeout: Optional[float],
    ) -> None:
        """The armed submit path: admit, enqueue, and only then count the
        batch admitted — a REFUSED enqueue (BackpressureTimeout, a sticky
        dispatcher raise) refunds the consumed tokens, so a producer that
        times out under pressure is not double-charged on the retry."""
        prio, rows = self._admit(stream_id, payload)
        try:
            self._submit_item(item, timeout)
        except BaseException:
            self._admission.refund(stream_id, rows, prio)
            raise
        self._stats.record_admission("admitted", prio)

    def _admit(self, stream_id: Optional[int], payload: Any) -> Tuple[int, int]:
        """Run one submit through the admission policy (armed path only);
        returns ``(priority, rows)`` for the caller's success/refund
        bookkeeping. The ``admission`` fault site models a transient
        control-plane failure — pure in its inputs, so the bounded retry
        re-checks cleanly; an actual rejection is counted by
        outcome/priority and re-raised to the producer with the policy's
        backoff hint."""
        pol = self._admission
        rows = self._item_rows_safe(payload)
        rows = 0 if rows is None else int(rows)
        inj = self._cfg.fault_injector

        def admit_once() -> int:
            if inj is not None:
                try:
                    inj.check("admission")
                except BaseException:  # noqa: BLE001 - recorded, then re-raised
                    self._stats.record_fault("admission")
                    if self._trace is not None:
                        self._trace.event("fault", trace=ENGINE_TRACE, site="admission")
                    raise
            return pol.admit(stream_id, rows)

        # a PRODUCER-side retry loop, deliberately not _retry_transient: that
        # policy belongs to the dispatcher thread — its retry events attribute
        # to the dispatcher's current group, and its jittered backoff draws
        # from the seeded stream chaos replay depends on; concurrent producer
        # draws would corrupt both. Admission retries attribute to the engine
        # track and back off without jitter.
        attempt = 0
        while True:
            try:
                prio = admit_once()
                return prio, rows
            except AdmissionRejected as e:
                self._stats.record_admission("shed" if e.shed else "rejected", e.priority)
                if self._trace is not None:
                    self._trace.event(
                        "admission_rejected", trace=ENGINE_TRACE,
                        priority=e.priority, shed=e.shed,
                        stream_id=stream_id,
                    )
                if e.shed and self._ladder is not None:
                    # liveness: when the only remaining traffic is the shed
                    # class, no group ever forms and the dispatcher never
                    # ticks — a shed rejection ticks instead (the tick is
                    # lock-guarded), so a recovered engine can de-escalate
                    # and re-admit the class without manual intervention
                    self._ladder_tick()
                raise
            except BaseException as e:  # noqa: BLE001 - classified by policy
                if not is_transient(e) or attempt >= self._cfg.max_retries:
                    raise
                attempt += 1
                self._stats.record_retry()
                if self._trace is not None:
                    self._trace.event("retry", trace=ENGINE_TRACE, attempt=attempt)
                delay = min(
                    max(0.0, self._cfg.backoff_max_ms),
                    max(0.0, self._cfg.backoff_base_ms) * (2 ** (attempt - 1)),
                ) / 1e3
                if delay > 0:
                    time.sleep(delay)

    def _submit_item(self, item: Any, timeout: Optional[float]) -> None:
        """Enqueue one queue item, tracing the submit when the recorder is
        on: the span's duration is the enqueue wait (backpressure made
        visible), and the trace id registered here is what the dispatcher's
        megabatch span links back to."""
        # enqueue stamp, recorded only for TIMEOUT-bearing submits (the one
        # consumer is BackpressureTimeout's oldest-item age; a plain blocking
        # submit keeps the disabled-path contract at one None-equivalent
        # check): popped at group pickup, on refused submits, and by the
        # dead-dispatcher drain — exactly the _trace_ids lifecycle
        if timeout is not None:
            self._submit_stamps[id(item)] = time.monotonic()
        tr = self._trace
        try:
            if tr is None:
                self._enqueue(item, timeout)
            else:
                tid = tr.new_trace()
                # the stamp starts the batch's queue residency clock: pickup time
                # minus THIS is the trace's queue_wait (under enqueue backpressure
                # it spans the blocked put too — the journey starts at submit, and
                # the coalesce root only begins at pickup, so nothing double-counts
                # into the end-to-end total)
                self._trace_ids[id(item)] = [tid, time.perf_counter()]
                ctx = {k: v for k, v in self._item_context(item).items() if v is not None}
                handle = tr.begin("submit", trace=tid, **ctx)
                try:
                    self._enqueue(item, timeout)
                except BaseException:
                    # a refused submit is no batch: drop the id so a later item
                    # reusing the same object identity cannot inherit it
                    self._trace_ids.pop(id(item), None)
                    raise
                tr.end(handle)
        except BaseException:
            self._submit_stamps.pop(id(item), None)
            raise
        self._stats.record_submitted()

    def _enqueue(self, item: Any, timeout: Optional[float]) -> None:
        if timeout is None:
            self._queue.put(item)
            return
        deadline = time.monotonic() + float(timeout)
        while True:
            # poll the sticky error each slice: a producer blocked on a full
            # queue must learn the dispatcher died, not deadlock forever
            self._raise_if_failed()
            try:
                # always attempt at least once — timeout=0 is the documented
                # "try, don't block" form and must succeed on a free queue
                self._queue.put_nowait(item)
                return
            except queue.Full:
                pass
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._raise_if_failed()
                alive = self._worker is not None and self._worker.is_alive()
                # satellite (ISSUE 11): name the congestion coordinates —
                # queue depth, in-flight device steps, and the oldest queued
                # item's age — so a producer's timeout is diagnosable from
                # the message alone, like EngineDispatchError's cursor/bucket
                now = time.monotonic()
                try:
                    # includes THIS item's own stamp: with no older tracked
                    # item the reported age is the caller's own wait — a
                    # floor, never an invention (only timeout-bearing
                    # submits stamp, so untracked items read as younger)
                    stamps = list(self._submit_stamps.values())
                except RuntimeError:  # racing dispatcher resize: age is best-effort
                    stamps = []
                oldest_s = (now - min(stamps)) if stamps else 0.0
                raise BackpressureTimeout(
                    f"submit() timed out after {timeout}s: queue full "
                    f"({self._queue.qsize()}/{max(1, self._cfg.max_queue)} batches), "
                    f"{len(self._inflight)} device steps in flight, oldest queued "
                    f"item {oldest_s:.3f}s old, and the dispatcher is "
                    f"{'alive but not draining' if alive else 'dead'}"
                )
            try:
                self._queue.put(item, timeout=min(0.05, remaining))
                return
            except queue.Full:
                continue

    def flush(self) -> None:
        """Block until every submitted batch is folded into the state.

        Survives a dispatcher that dies MID-FLUSH (fatal fault): the wait
        re-checks thread liveness, drains the orphaned backlog, and the
        sticky error is raised instead of hanging the caller forever."""
        self._raise_if_failed()
        self._join_queue()
        with self._state_lock:  # a concurrent step must not donate the
            jax.block_until_ready(self._state)  # buffers out from under us
        self._raise_if_failed()

    def result(self) -> Any:
        """Flush, then run the AOT-compiled compute on the accumulated state.

        Under deferred sync the flush is followed by the boundary merge (one
        fused collective bundle), so the value reflects every batch submitted
        before the call — same freshness as step sync; what deferred mode
        trades away is only the GLOBAL consistency of the carried state
        BETWEEN boundaries, never of a returned result."""
        tr = self._trace
        handle = tr.begin("result", trace=ENGINE_TRACE) if tr is not None else None
        self.flush()
        with self._state_lock:
            state = self._merged_state() if self._deferred else self._state
            value = self._compute_program()(state, *self._compute_extra())
        if handle is not None:
            jax.block_until_ready(value)  # the SLO observable is value-in-hand
            tr.observe("result_latency_us", tr.end(handle))
        return value

    def state(self) -> Any:
        """A defensive copy of the accumulated (global) LOGICAL state pytree,
        after a flush. Copied because the live buffers are DONATED into the
        next update step — a borrowed reference would read as deleted after
        the caller submits more traffic. Arenas are unpacked: callers see the
        metric's own state layout either way. Under deferred sync this is the
        MERGED global state — memoized non-donated program outputs (no copy
        needed; at most one boundary collective per state version), with
        ``cat`` buffers concatenated across shards, so their leading dim is
        world x the per-shard capacity."""
        self.flush()
        with self._state_lock:
            if self._deferred:
                # no copy needed: the merged arrays are non-donated program
                # outputs — immutable and never deleted by later steps
                return self._merged_state()
            return jax.tree.map(lambda x: jnp.array(x, copy=True), self._unpack(self._state))

    @property
    def steps(self) -> int:
        return self._step

    @property
    def stats(self) -> EngineStats:
        return self._stats

    @property
    def aot_cache(self) -> AotCache:
        return self._aot

    @property
    def arena_layout(self) -> Optional[ArenaLayout]:
        return self._layout

    @property
    def window(self) -> Optional[WindowPolicy]:
        """The active (rotating) window policy — None for cumulative serving."""
        return self._window

    @property
    def pane_cursor(self) -> int:
        """Current pane slot of the ring (always 0 for ewma/cumulative)."""
        return self._pane_cursor

    @property
    def rotations(self) -> int:
        """Pane rotations performed since construction/reset/restore base."""
        return self._rotations

    @property
    def drift(self) -> Optional[DriftDetector]:
        """The wired drift detector (None when drift tracking is off)."""
        return self._drift

    @property
    def trace(self) -> Optional[TraceRecorder]:
        """The flight recorder this engine reports spans to (None = off)."""
        return self._trace

    def _model_host_sections(self) -> Optional[List[Dict[str, Any]]]:
        """Telemetry snapshots of attached embedded-model hosts (ISSUE 19).

        Attachment is by plain attribute (``engine.model_host = host`` or
        ``engine.model_hosts = [..]``) — the same contract the analysis
        plane's ``host-collectives-pinned`` audit discovers hosts by."""
        hosts = getattr(self, "model_hosts", None)
        if not hosts:
            host = getattr(self, "model_host", None)
            hosts = [host] if host is not None else []
        return [h.telemetry() for h in hosts] or None

    def telemetry(self) -> Dict[str, Any]:
        doc = self._stats.summary(self._aot.stats())
        if self._trace is not None:
            doc["trace"] = self._trace.summary()
        hosts = self._model_host_sections()
        if hosts:
            doc["model_host"] = hosts
        return doc

    def export_telemetry(self, path: str) -> None:
        extra: Dict[str, Any] = {}
        if self._trace is not None:
            extra["trace"] = self._trace.summary()
        hosts = self._model_host_sections()
        if hosts:
            extra["model_host"] = hosts
        self._stats.export(path, self._aot.stats(), extra=extra or None)

    def export_trace(self, path: str) -> str:
        """Write the flight recorder's Chrome/Perfetto trace-event JSON to
        ``path`` (by sidecar-hygiene convention: ``out/trace_*.json``). Load
        it at https://ui.perfetto.dev — host threads render as tracks, and
        every megabatch span carries flow arrows back to the submit spans it
        absorbed. Requires ``EngineConfig(trace=TraceRecorder(...))``."""
        if self._trace is None:
            raise MetricsTPUUserError(
                "export_trace() requires a flight recorder: construct the engine "
                "with EngineConfig(trace=TraceRecorder(...))"
            )
        return self._trace.export(path)

    def metrics_text(self) -> str:
        """An OpenMetrics/Prometheus text snapshot of this engine: lifetime
        counters (steps, rows, faults by site, recovery actions, quarantine,
        snapshots, compile cache) plus — when the flight recorder is on —
        real fixed-bucket latency histograms (step/queue/result/merge),
        folded through the library's own ``histogram_accumulate`` path."""
        s = self._stats
        counters = {
            "steps": s.steps,
            "batches_submitted": s.batches_submitted,
            "batches_coalesced": s.batches_coalesced,
            "megasteps": s.megasteps,
            "rows_in": s.rows_in,
            "rows_padded": s.rows_padded,
            "snapshots": s.snapshots,
            "resumes": s.resumes,
            "merges": s.merges,
            "retries": s.retries,
            "rollbacks": s.rollbacks,
            "kernel_demotions": s.kernel_demotions,
            "coalesce_degraded": s.coalesce_degraded,
            "coalesce_shrinks": s.coalesce_shrinks,
            "watchdog_timeouts": s.watchdog_timeouts,
            "quarantined_batches": s.quarantined_batches,
            "quarantined_rows": s.quarantined_rows,
            "snapshot_failures": s.snapshot_failures,
            "snapshot_fallbacks": s.snapshot_fallbacks,
        }
        aot = self._aot.stats()
        counters["compile_cache_hits"] = aot["hits"]
        counters["compile_cache_misses"] = aot["misses"]
        labeled: Dict[str, Any] = {}
        faults = s.faults_by_site()  # locked snapshot: producers may be firing
        if faults:
            labeled["faults_injected"] = ("site", faults)
        fallbacks = s.kernel_fallbacks_by_reason()
        if fallbacks:
            # megastep degradation verdicts (ISSUE 16): how much state runs
            # OFF the fused whole-step path, keyed "engine:<reason>" /
            # "dtype.<key>:<reason>" — present only on engines that judged a
            # fallback, so every other exposition stays byte-stable
            labeled["kernel_fallbacks"] = ("reason", fallbacks)
        if s.sync_payload_exact_bytes or s.sync_payload_quant_bytes:
            # mesh engines only (non-mesh engines never record a payload):
            # bytes one shard contributed per fused sync, split by rider —
            # the quantized-vs-exact bandwidth observable (ISSUE 10)
            labeled["sync_payload_bytes"] = (
                "kind",
                {
                    "exact": s.sync_payload_exact_bytes,
                    "quantized": s.sync_payload_quant_bytes,
                },
            )
        gauges = {"compiled_programs": aot["programs"]}
        admission = s.admission_summary()
        if admission is not None:
            # admission-control families (ISSUE 11): verdicts by priority
            # class + the ladder's gauge/counter pair — present only when an
            # admission policy or ladder actually ran, so every pre-existing
            # engine's exposition stays byte-stable
            for fam, key in (
                ("admission_admitted", "admitted_by_priority"),
                ("admission_rejected", "rejected_by_priority"),
                ("admission_shed", "shed_by_priority"),
            ):
                if admission[key]:
                    labeled[fam] = ("priority", admission[key])
            counters["ladder_transitions"] = admission["ladder_transitions"]
            counters["deferred_reads"] = admission["deferred_reads"]
            gauges["ladder_level"] = admission["ladder_level"]
        if s.reshards:
            counters["reshards"] = s.reshards
        if s.paging_summary() is not None:
            # stream-sharded serving: routing + LRU-paging telemetry joins the
            # exposition only when the engine actually routed (non-sharded
            # engines keep their surface byte-stable)
            counters.update(
                routed_steps=s.routed_steps,
                page_hits=s.page_hits,
                page_faults=s.page_faults,
                page_ins=s.page_ins,
                page_outs=s.page_outs,
            )
            gauges["resident_streams"] = s.resident_streams
            gauges["spilled_streams"] = s.spilled_streams
            gauges["spilled_bytes"] = s.spilled_bytes
        if s.windows_summary() is not None:
            # windowed semantics (ISSUE 13): rotation/decay/drift families
            # join the exposition only for windowed engines — every
            # cumulative engine's surface stays byte-stable
            counters["pane_rotations"] = s.pane_rotations
            counters["ewma_decays"] = s.ewma_decays
            counters["drift_evals"] = s.drift_evals
            counters["drift_alarms"] = s.drift_alarms
            gauges["live_panes"] = s.live_panes
            gauges["pane_cursor"] = s.pane_cursor
        ragged = s.ragged_summary()
        if ragged is not None:
            # ragged serving (ISSUE 17): group-keyed ingestion families join
            # the exposition only for ragged engines — every stream engine's
            # surface stays byte-stable
            counters["ragged_batches"] = ragged["batches"]
            counters["ragged_rows"] = ragged["rows"]
            counters["ragged_groups_touched"] = ragged["groups_touched"]
            counters["ragged_overflows"] = ragged["overflows"]
            # aggregate reads (ISSUE 18): which path served, and how many
            # paged sweep blocks the group_shard aggregates dispatched
            counters["ragged_agg_device_reads"] = ragged["agg_device_reads"]
            counters["ragged_agg_oracle_reads"] = ragged["agg_oracle_reads"]
            counters["ragged_agg_blocks"] = ragged["agg_blocks"]
            gauges["ragged_groups"] = ragged["groups"]
            gauges["ragged_capacity"] = ragged["capacity"]
        hists = self._trace.histograms() if self._trace is not None else ()
        return render_openmetrics(
            counters, hists, labeled_counters=labeled or None, gauges=gauges
        )

    def reset(self) -> None:
        """Fresh accumulation; compiled programs are kept.

        Also the RECOVERY path for a sticky dispatcher failure (the other is
        :meth:`restore`): the queue is drained — a failed dispatcher discards
        the backlog without folding it — the error is cleared, and the
        accumulation starts over. Without a failure this flushes normally
        (every pending batch lands before the state is replaced)."""
        self._join_queue()
        with self._state_lock:
            self._reset_locked()

    def _reset_locked(self) -> None:
        """The state-swap half of :meth:`reset`, under ONE state-lock hold —
        subclasses with sibling tables (the stream-sharded engine's pager)
        extend this so no dispatched group can ever observe fresh state next
        to stale bookkeeping."""
        self._error = None
        self._inflight.clear()
        self._result_cache.clear()
        self._state = self._put_state(self._init_state_tree())
        self._state_version += 1
        self._step = 0
        self._batches_done = 0
        if self._window is not None:
            self._pane_cursor = 0
            self._rotations = 0
            self._last_rotate_batches = 0
            self._pane_open_cursor = 0
            self._last_rotate_time = self._win_clock()
            self._stats.pane_cursor = 0
            self._stats.live_panes = 1

    # ---------------------------------------------------------------------- recovery

    def snapshot(self) -> str:
        """Flush and write one crash-safe snapshot now."""
        if not self._cfg.snapshot_dir:
            raise MetricsTPUUserError("snapshot() requires config.snapshot_dir")
        self.flush()
        return self._save_snapshot()

    def _save_snapshot(self) -> str:
        with self._state_lock:
            return self._save_snapshot_locked()

    def _save_snapshot_locked(self) -> str:
        # a write-site fault fires BEFORE any bytes land: LATEST still points
        # at the previous complete generation (the atomic-pointer contract),
        # so a failed save degrades recovery granularity, never correctness
        tr = self._trace
        snap_handle = (
            tr.begin("snapshot_write", trace=ENGINE_TRACE, step=self._step)
            if tr is not None
            else None
        )
        self._fault("snapshot_write")
        host_state, meta = self._snapshot_doc()
        path = save_snapshot(
            self._cfg.snapshot_dir,
            host_state,
            meta,
            keep=self._cfg.snapshot_keep,
            host_attrs=self._metric.host_compute_attrs(),
        )
        self._stats.snapshots += 1
        if snap_handle is not None:
            tr.end(snap_handle)
        inj = self._cfg.fault_injector
        if inj is not None and inj.fire("snapshot_corrupt"):
            # bit-rot chaos: the save SUCCEEDED (LATEST points here) and then
            # the payload rots on disk — the case the integrity sidecar and
            # restore()'s generation-ring fallback exist for
            self._stats.record_fault("snapshot_corrupt")
            if tr is not None:
                tr.event("fault", site="snapshot_corrupt")
            corrupt_snapshot(path, inj.snapshot_rng())
        return path

    def _snapshot_doc(self) -> Tuple[Any, Dict[str, Any]]:
        """``(host_state, meta)``: the engine's durable form plus its
        topology provenance — ONE builder shared by the on-disk snapshot
        writer and :meth:`reshard`'s in-memory capture, so the live-reshard
        path IS snapshot-through-the-restore-matrix, not a parallel codec.

        The carried form: arena = 1 payload/dtype. Under deferred sync the
        payload is the SHARD-STACKED arena — every shard's local state, i.e.
        full provenance: the merged view is derivable (merge_stacked_states)
        but the locals are not recoverable from it, and exact kill/resume
        replay needs the locals (each shard must resume with ITS rows)."""
        host_state = self._snapshot_state()
        meta = {
            "step": self._step,
            "batches_done": self._batches_done,
            "rows_in": self._stats.rows_in,
            "rows_padded": self._stats.rows_padded,
            # a compressed snapshot stores the LOGICAL (possibly shard-
            # stacked) tree with codec-wrapped leaves, never the raw arena
            "packed": int(self._layout is not None and not self._compress),
            "arena_fp": self._layout.fingerprint() if self._layout is not None else "",
            "mesh_sync": self._sync_tag() if self._cfg.mesh is not None else "single",
            "world": self._world if self._deferred else 1,
            # host topology rides ALONGSIDE the world/shard provenance: a
            # fleet host's piece names which host of how many wrote it (and
            # the homing rule streams follow), so the restore matrix can
            # route it — absent fields on pre-fleet snapshots read back as
            # the single-host defaults
            "num_hosts": self._fleet_hosts,
            "process_id": self._fleet_pid,
        }
        if self._fleet_hosts > 1:
            meta["host_homing"] = "sid_mod_num_hosts"
        if self._fleet_cut is not None:
            meta["fleet_cut"] = int(self._fleet_cut)
            meta["fleet_plan_cursor"] = int(self._fleet_plan_cursor)
        if self._compress:
            from metrics_tpu.engine.quantize import CODEC_ID

            meta["codec"] = CODEC_ID
            meta["codec_fp"] = self._precision_tag
        if self._window is not None:
            # pane-ring provenance (ISSUE 13): the policy fingerprint is the
            # cross-policy refusal key; cursor + rotation marks let a
            # restored engine resume mid-ring without re-rotating the
            # boundary (pane_fill = batches folded into the current pane)
            meta["window"] = self._window.fingerprint()
            meta["panes"] = self._panes
            meta["pane_cursor"] = self._pane_cursor
            meta["rotations"] = self._rotations
            meta["pane_fill"] = self._batches_done - self._pane_open_cursor
        meta.update(self._snapshot_meta_extra())
        return host_state, meta

    def _snapshot_state(self) -> Any:
        """The host-side state payload a snapshot carries — by default the
        carried form itself (packed arena / shard-stacked buffers). The
        stream-sharded engine overrides this to bundle its resident arena
        WITH the pager's spilled rows and slot tables (paged rows must be
        covered by kill/resume).

        With ``config.compress_payloads`` the payload is instead the LOGICAL
        (shard-stacked under deferred sync) tree with the metric's quantized-
        policy leaves codec-wrapped (``engine/quantize.py``) — snapshot disk
        scales with the quantized footprint. The encode is a pure function of
        the fetched host tree, so an injected ``quant_encode`` transient
        retries without double-applying scales."""
        if not self._compress:
            return jax.device_get(self._state)
        from metrics_tpu.engine.quantize import encode_state_tree

        if self._deferred:
            tree = (
                self._layout.unpack_stacked(
                    self._state, lead=2 if self._win_stacked else 1
                )
                if self._layout is not None
                else self._state
            )
        else:
            tree = self._unpack(self._state)
        host = jax.device_get(tree)

        def encode_once() -> Any:
            self._fault("quant_encode")
            return encode_state_tree(self._metric, host)

        return self._retry_transient(encode_once)

    def _snapshot_meta_extra(self) -> Dict[str, Any]:
        """Extra provenance meta a subclass folds into every snapshot (the
        stream-sharded engine adds its stream/shard/residency topology)."""
        return {}

    def restore(self, directory_or_path: Optional[str] = None) -> Dict[str, Any]:
        """Resume from the newest complete snapshot (engine must be idle).

        Returns the snapshot's meta dict — ``batches_done`` is the replay
        cursor: re-submit the stream from that batch onward and the final
        result is exactly the uninterrupted one. Host-derived compute
        attributes (e.g. ``Accuracy``'s input-mode latch) are restored too,
        so ``result()`` works immediately — no post-restore batch needed.

        Also a RECOVERY path for a sticky dispatcher failure: the backlog is
        drained unfolded and the error is cleared once the snapshot state is
        committed (a failed load leaves the engine — error included — as it
        was). Loads through the generation-ring FALLBACK: a corrupted or
        truncated newest payload (typed ``SnapshotCorruptError``) falls back
        to the newest VALID generation — the returned ``batches_done`` is
        then the OLDER cursor, and replay from it is exact; the fallback is
        counted in ``stats.snapshot_fallbacks``. Transient read failures
        retry with backoff inside this call.
        """
        self._join_queue()  # drain; a sticky-failed (or dead) dispatcher discards
        tr = self._trace
        restore_handle = (
            tr.begin("snapshot_restore", trace=ENGINE_TRACE) if tr is not None else None
        )

        def load_once() -> Tuple[Any, Dict[str, Any]]:
            self._fault("snapshot_read")
            return load_snapshot(directory_or_path or self._cfg.snapshot_dir, fallback=True)

        state, meta = self._retry_transient(load_once)
        self._restore_commit(state, meta)
        if restore_handle is not None:
            tr.end(
                restore_handle,
                generations_skipped=int(meta.get("generations_skipped", 0) or 0),
                cursor=self._batches_done,
            )
        return meta

    def _check_window_provenance(self, meta: Dict[str, Any]) -> None:
        """The cross-policy refusal (ISSUE 13): a pane ring is only
        replayable under the policy that built it — pane boundaries, ring
        depth, and decay factors are all part of what the buffers MEAN. A
        snapshot without window provenance is a cumulative snapshot (empty
        tag), so windowed<->unwindowed mismatches refuse symmetrically."""
        snap_win = str(meta.get("window", "") or "")
        eng_win = self._window.fingerprint() if self._window is not None else ""
        if snap_win != eng_win:
            raise MetricsTPUUserError(
                f"snapshot window policy {snap_win or 'cumulative'!r} does not match "
                f"this engine's {eng_win or 'cumulative'!r}: pane rings are only "
                "replayable under the policy that built them — restore into an "
                "engine constructed with the same WindowPolicy"
            )

    def _restore_commit(self, state: Any, meta: Dict[str, Any]) -> None:
        """Validate a loaded snapshot against this engine's mode/topology and
        commit it (the restore matrix). Subclasses reroute snapshots carrying
        other topologies (the stream-sharded engine's restore matrix) before
        falling back here."""
        self._check_window_provenance(meta)
        # codec-wrapped (compressed) payloads decode FIRST — the wrapped
        # leaves are self-describing, so every path of the restore matrix
        # (same-world verbatim, host merge, shard-0 embed) sees plain arrays.
        # Decode is pure in its input: a quant_decode transient retries clean.
        if str(meta.get("codec", "") or ""):
            from metrics_tpu.engine.quantize import decode_state_tree

            def decode_once() -> Any:
                self._fault("quant_decode")
                return decode_state_tree(state)

            state = self._retry_transient(decode_once)
        # VALIDATE before mutating anything: a failed restore must leave the
        # live engine (metric attrs, fingerprint, memo, state) untouched.
        # Host topology first (ISSUE 15): a fleet host's piece is PARTIAL
        # state (one host's local accumulation) — committing it verbatim into
        # an engine with a different host topology would silently serve a
        # fraction of the traffic as if it were all of it. Missing fields
        # default to single-host, so every pre-fleet snapshot restores
        # unchanged.
        snap_hosts = int(meta.get("num_hosts", 1) or 1)
        snap_pid = int(meta.get("process_id", 0) or 0)
        if snap_hosts != self._fleet_hosts or snap_pid != self._fleet_pid:
            raise MetricsTPUUserError(
                f"snapshot host topology (num_hosts={snap_hosts}, "
                f"process_id={snap_pid}) does not match this engine's "
                f"(num_hosts={self._fleet_hosts}, process_id={self._fleet_pid}): "
                "a fleet host piece restores only into the SAME host of a "
                "same-size fleet — merge a whole fleet snapshot into a "
                "single-process engine with engine.fleet.restore_fleet_into(), "
                "or adopt a single-process snapshot into a fleet with "
                "FleetEngine.adopt_single()"
            )
        packed = bool(int(meta.get("packed", 0)))
        snap_deferred = str(meta.get("mesh_sync", "") or "") == "deferred"
        snap_world = int(meta.get("world", 1))
        if packed:
            if self._layout is None:
                raise MetricsTPUUserError(
                    "snapshot holds a packed arena but this engine runs with use_arena=False; "
                    "enable the arena (or re-snapshot unpacked) to restore it"
                )
            # buffer shape/dtype check alone cannot catch permuted same-dtype
            # leaves (identical buffers, scrambled unpack) — the layout
            # FINGERPRINT in meta is the sufficient check
            saved_fp = str(meta.get("arena_fp", "") or "")
            shape_ok = self._layout.matches(
                state,
                world=snap_world if snap_deferred else None,
                panes=self._panes if self._win_stacked else None,
            )
            if not shape_ok or (saved_fp and saved_fp != self._layout.fingerprint()):
                raise MetricsTPUUserError(
                    "snapshot arena does not match this metric's layout "
                    f"({self._layout!r}); was the metric reconfigured since the snapshot?"
                )
        # device-commit FIRST: on the unpacked path _put_state packs, which is
        # the last fallible step — the metric must not be mutated before it.
        # The mode/topology matrix:
        #   deferred snapshot -> same-world deferred engine: shard provenance
        #     restores VERBATIM (each shard resumes with exactly its local
        #     state — replay from batches_done is bit-exact);
        #   deferred snapshot -> anything else: the shard locals merge on the
        #     host (merge_stacked_states) into the global state — exact for
        #     dist_reduce_fx-mergeable states; refused when the merged shapes
        #     no longer fit the engine's template (cat buffers grow with the
        #     shard count — those need a same-world deferred engine);
        #   step/single snapshot -> deferred engine: the global state embeds
        #     into shard 0 with identity states elsewhere (the merge folds
        #     the identities away, so compute is unchanged).
        if snap_deferred and self._deferred and snap_world == self._world:
            new_state = self._put_state(state, packed=packed, stacked=True)
        elif snap_deferred:
            stacked_tree = (
                self._layout.unpack_stacked(state, lead=2 if self._win_stacked else 1)
                if packed
                else state
            )
            logical = self._metric.merge_stacked_states(stacked_tree)
            template_leaves, template_def = jax.tree_util.tree_flatten(self._abstract_state_tree())
            leaves, treedef = jax.tree_util.tree_flatten(logical)
            if treedef != template_def or any(
                tuple(l.shape) != tuple(t.shape) for l, t in zip(leaves, template_leaves)
            ):
                raise MetricsTPUUserError(
                    f"deferred snapshot (world={snap_world}) merges to state shapes this "
                    f"engine cannot carry (cat-state buffers scale with the shard count); "
                    "restore it into a deferred engine with the same mesh size"
                )
            new_state = (
                self._put_state(self._shard0_stack(logical), stacked=True)
                if self._deferred
                else self._put_state(logical)
            )
        elif self._deferred:
            logical = self._unpack(state) if packed else state
            new_state = self._put_state(self._shard0_stack(logical), stacked=True)
        else:
            new_state = self._put_state(state, packed=packed)
        self._finish_restore(new_state, meta)

    def _finish_restore(self, new_state: Any, meta: Dict[str, Any]) -> None:
        """Atomically commit a validated restored state + the replay cursor
        (shared by every path of the restore matrix)."""
        with self._state_lock:
            attrs = meta.get("host_attrs")
            if attrs:
                self._metric.restore_host_compute_attrs(attrs)
                # the fingerprint covers host attrs (they are trace constants);
                # re-derive it so program keys reflect the restored values
                # (live engines derive the same post-latch fingerprint via
                # _latch_host_attrs on their first batch)
                self._metric_fp = metric_fingerprint(self._metric)
                self._program_memo.clear()
            # a pre-traffic snapshot restores attrs that are still None — the
            # first-batch latch must stay armed for those, or two restored
            # engines sharing a cache could collide on an unlatched key
            self._needs_attr_latch = any(
                v is None for v in self._metric.host_compute_attrs().values()
            )
            self._state = new_state
            self._state_version += 1
            self._error = None
            self._inflight.clear()
            self._result_cache.clear()
            # the replay cursor commits in the SAME critical section as the
            # state: a batch the dispatcher folds right after the lock drops
            # must land on top of both, or replay double-counts it
            self._step = int(meta.get("step", 0))
            self._batches_done = int(meta.get("batches_done", self._step))
            if self._window is not None:
                # resume mid-ring: cursor + rotation count restore verbatim;
                # the batch-cadence mark re-derives from the cursor so the
                # next rotation lands at the ORIGINAL pane boundary, and the
                # time-cadence clock restarts fresh (wall time does not
                # replay — the injectable clock owns that determinism)
                self._pane_cursor = int(meta.get("pane_cursor", 0))
                self._rotations = int(meta.get("rotations", 0))
                self._pane_open_cursor = self._batches_done - int(
                    meta.get("pane_fill", 0)
                )
                self._last_rotate_batches = self._pane_open_cursor
                self._last_rotate_time = self._win_clock()
                self._stats.pane_cursor = self._pane_cursor
                self._stats.live_panes = min(self._rotations + 1, self._panes)
            self._stats.rows_in = int(meta.get("rows_in", self._stats.rows_in))
            self._stats.rows_padded = int(meta.get("rows_padded", self._stats.rows_padded))
            self._stats.resumes += 1
            if int(meta.get("generations_skipped", 0) or 0) > 0:
                self._stats.snapshot_fallbacks += 1

    # -------------------------------------------------------------------- dispatcher

    def _run(self) -> None:
        pending: Optional[Any] = None
        while True:
            if pending is not None:
                first, wait_us = pending, 0.0
                pending = None
            else:
                t0 = time.perf_counter()
                first = self._queue.get()
                wait_us = (time.perf_counter() - t0) * 1e6
            if first is _STOP:
                self._queue.task_done()
                return
            group, pending, saw_stop, fatal = [first], None, False, False
            if self._error is None:
                group, pending, saw_stop, drain_wait_us = self._coalesce_group(first)
                wait_us += drain_wait_us  # window blocking is queue wait too
            tids = self._pop_trace_ids(group)  # even when draining: no leaks
            self._pop_stamps(group)
            try:
                if self._error is None:  # after a failure: drain without work
                    self._process_group(group, wait_us, tids)
                    if self._ladder is not None:
                        # the degradation ladder evaluates once per processed
                        # group, BEFORE task_done: a flush() that joined the
                        # queue must observe the settled ladder level (the
                        # tick swallows its own failures into the sticky
                        # error, never killing the dispatcher)
                        self._ladder_tick()
            except BaseException as e:  # noqa: BLE001 - surfaced via _raise_if_failed
                _attach_ctx(e, cursor=self._batches_done, **self._group_context(group))
                self._error = e
                fatal = isinstance(e, InjectedFault) and e.fatal
            finally:
                for _ in group:
                    self._queue.task_done()
            if fatal:
                # a FATAL fault models the dispatcher process dying outright:
                # the thread exits without draining, the bounded queue fills,
                # and producers learn of it via submit(timeout=)'s sticky
                # raise; recovery entry points (reset/restore/flush) drain
                # the backlog themselves (_join_queue). Items this loop
                # already DEQUEUED — the coalescer's incompatible look-ahead
                # and a consumed _STOP — must still count as done here, or
                # the queue's unfinished counter stays inflated forever and
                # every join after a successful reset() hangs.
                if pending is not None:
                    self._pop_trace_ids([pending])  # dropped item: free its id
                    self._pop_stamps([pending])
                    self._queue.task_done()
                if saw_stop:
                    self._queue.task_done()
                return
            if saw_stop:
                self._queue.task_done()
                return

    def _group_context(self, group: List[Any]) -> Dict[str, Any]:
        """Extra failure context for a group (subclasses add stream ids)."""
        return {}

    def _pop_trace_ids(self, group: List[Any]) -> Optional[List[Tuple[str, float]]]:
        """Collect (and release) the submit trace ids of a picked-up group —
        the links its megabatch span carries — each with the batch's QUEUE
        RESIDENCY in µs (pickup minus submit stamp: the time THIS batch's
        journey spent waiting, not the dispatcher's idle block in ``get()``,
        which belongs to stats' starvation attribution, never to a trace).
        None when tracing is off."""
        if self._trace is None:
            return None
        now = time.perf_counter()
        out: List[Tuple[str, float]] = []
        for it in group:
            entry = self._trace_ids.pop(id(it), None)
            if entry is not None:
                out.append((entry[0], (now - entry[1]) * 1e6))
        return out

    def _pop_stamps(self, group: List[Any]) -> None:
        """Release the enqueue stamps of a picked-up (or dropped) group —
        one truthiness check when no timeout-bearing submit ever stamped."""
        if not self._submit_stamps:
            return
        for it in group:
            self._submit_stamps.pop(id(it), None)

    def _join_queue(self) -> None:
        """``queue.join()`` that survives a DEAD dispatcher — including one
        that dies WHILE we wait. A live worker drains normally (we wait on
        the queue's all-tasks-done condition in slices, re-checking thread
        liveness each slice); once no live worker exists — a fatal fault
        killed it, or ``stop()`` already cleared it while a backlog (possibly
        with a stale ``_STOP``) remains — the backlog is drained here, since
        unfinished items would otherwise pin ``join()`` (and with it flush/
        reset/restore) forever."""
        while self._worker is not None and self._worker.is_alive():
            with self._queue.all_tasks_done:
                if self._queue.unfinished_tasks == 0:
                    return
                self._queue.all_tasks_done.wait(timeout=0.1)
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            # a drained item is a dropped batch: free its submit trace id,
            # or _trace_ids grows by one entry per recovery cycle forever
            self._trace_ids.pop(id(item), None)
            self._submit_stamps.pop(id(item), None)
            self._queue.task_done()
        # items a dead dispatcher dequeued but never finished cannot be
        # recovered; zero the counter so later joins see a consistent queue
        with self._queue.all_tasks_done:
            if self._queue.unfinished_tasks:
                self._queue.unfinished_tasks = 0
                self._queue.all_tasks_done.notify_all()

    # ------------------------------------------------------------------- coalescing

    def _item_rows(self, item: Any) -> int:
        n = infer_batch_size(item)
        if n is None:
            raise MetricsTPUUserError(
                "submit() needs at least one array argument with a batch dimension"
            )
        return int(n)

    def _item_rows_safe(self, item: Any) -> Optional[int]:
        """Row count, or None for malformed items — used on the coalesce path,
        which must never raise (errors surface through the processing path's
        sticky-failure machinery instead)."""
        try:
            return self._item_rows(item)
        except Exception:  # noqa: BLE001
            return None

    def _coalesce_group(self, first: Any) -> Tuple[List[Any], Optional[Any], bool, float]:
        """Opportunistically drain further compatible queued batches behind
        ``first``. Returns ``(group, pending_incompatible_item, saw_stop,
        drain_wait_us)`` — the last is time spent BLOCKED waiting for more
        traffic inside the coalesce window, reported so the telemetry's
        queue-wait share (and the regime label) stays honest when
        ``coalesce_window_ms > 0``. Bounded three ways: ``config.coalesce``
        batches, the top bucket's row count (a fuller megabatch would just
        re-chunk), and the next snapshot boundary (cadence must stay
        batch-exact)."""
        limit = max(1, int(self._cfg.coalesce))
        if self._cfg.snapshot_every > 0:
            limit = min(
                limit,
                self._cfg.snapshot_every - (self._batches_done % self._cfg.snapshot_every),
            )
        if self._window is not None and self._window.pane_batches > 0:
            # a megabatch must not straddle a pane boundary: rows past the
            # boundary belong to the NEXT pane (same exactness contract as
            # the snapshot cadence; time-cadence panes rotate between groups
            # by construction)
            limit = min(
                limit,
                self._window.pane_batches
                - (self._batches_done - self._last_rotate_batches),
            )
        group = [first]
        if limit <= 1:
            return group, None, False, 0.0
        inj = self._cfg.fault_injector
        if inj is not None and inj.fire("coalesce"):
            # graceful degradation, not an error: this path must NEVER raise
            # (an escape would kill the dispatcher and deadlock flush) — a
            # coalesce-machinery fault just serves the group as singletons
            self._stats.record_fault("coalesce")
            self._stats.coalesce_degraded += 1
            if self._trace is not None:
                self._trace.event("fault", site="coalesce")
            return group, None, False, 0.0
        rows = self._item_rows_safe(first)
        if rows is None:  # malformed: run alone so the error surfaces cleanly
            return group, None, False, 0.0
        top = self._policy.buckets[-1]
        deadline = time.perf_counter() + self._cfg.coalesce_window_ms / 1e3
        waited = 0.0
        ref = first if rows else None
        while len(group) < limit and rows < top:
            try:
                timeout = deadline - time.perf_counter()
                if timeout > 0:
                    t0 = time.perf_counter()
                    try:
                        item = self._queue.get(timeout=timeout)
                    finally:
                        waited += time.perf_counter() - t0
                else:
                    item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is _STOP:
                return group, None, True, waited * 1e6
            n = self._item_rows_safe(item)
            if n is None:
                return group, item, False, waited * 1e6
            if n == 0:
                group.append(item)  # cursor-only; nothing to concatenate
                continue
            if ref is not None and not self._coalescible(ref, item):
                return group, item, False, waited * 1e6
            if ref is None:
                ref = item
            group.append(item)
            rows += n
        return group, None, False, waited * 1e6

    def _coalescible(self, ref: Any, item: Any) -> bool:
        """Can ``item`` concatenate behind ``ref`` into one megabatch? Same
        pytree structure, batch-carried leaves agreeing on trailing shape and
        dtype, and non-batch (broadcast/config) leaves EQUAL — a differing
        broadcast argument changes the math and must run as its own step.

        MUST NOT RAISE (it runs outside the dispatcher's sticky-error capture;
        an escape would kill the thread and deadlock ``flush``): any exotic
        leaf that breaks a probe just doesn't coalesce — the item then runs as
        its own step, where a genuine error surfaces through the normal path.
        """
        try:
            ref_leaves, ref_def = jax.tree_util.tree_flatten(ref)
            leaves, treedef = jax.tree_util.tree_flatten(item)
            if treedef != ref_def or len(leaves) != len(ref_leaves):
                return False
            n_ref = infer_batch_size(ref_leaves)
            n_item = infer_batch_size(leaves)
            for rl, il in zip(ref_leaves, leaves):
                rb, ib = is_batch_leaf(rl, n_ref), is_batch_leaf(il, n_item)
                if rb != ib:
                    return False
                if rb:
                    if rl.shape[1:] != il.shape[1:] or np.dtype(rl.dtype) != np.dtype(il.dtype):
                        return False
                elif not _aux_leaves_equal(rl, il):
                    return False
            return True
        except Exception:  # noqa: BLE001 - don't coalesce what we can't probe
            return False

    def _merge_sized(
        self, nonempty: List[Tuple[Any, int]]
    ) -> Optional[Tuple[Tuple[Any, ...], Dict[str, Any]]]:
        """Concatenate pre-sized non-empty items into one (args, kwargs)
        megabatch (host numpy; this runs on the dispatcher thread, overlapped
        with the device via async dispatch). None when the group was all
        empty. Row counts come in from the caller — each item is tree-
        flattened for sizing exactly once per dispatch."""
        return self._concat_sized(nonempty)

    @staticmethod
    def _concat_sized(
        nonempty: List[Tuple[Any, int]],
    ) -> Optional[Tuple[Tuple[Any, ...], Dict[str, Any]]]:
        if not nonempty:
            return None
        if len(nonempty) == 1:
            return nonempty[0][0]
        flat = [jax.tree_util.tree_flatten(it) for it, _ in nonempty]
        treedef = flat[0][1]
        n0 = nonempty[0][1]
        out_leaves: List[Any] = []
        for i, leaf0 in enumerate(flat[0][0]):
            if is_batch_leaf(leaf0, n0):
                out_leaves.append(
                    np.concatenate([np.asarray(leaves[i]) for leaves, _ in flat], axis=0)
                )
            else:
                out_leaves.append(leaf0)
        return jax.tree_util.tree_unflatten(treedef, out_leaves)

    # ----------------------------------------------------------------- fault plumbing

    def _fault(self, site: str) -> None:
        """Consult the chaos plan at an injection boundary; a fired fault is
        counted in stats and raised (``InjectedFault``/``StepTimeoutError``)
        for the surrounding recovery machinery to handle."""
        inj = self._cfg.fault_injector
        if inj is None:
            return
        try:
            inj.check(site)
        except BaseException as e:  # noqa: BLE001 - recorded, then re-raised
            self._stats.record_fault(site)
            if self._trace is not None:
                self._trace.event(
                    "fault", trace=self._group_tid or ENGINE_TRACE, site=site,
                    occurrence=getattr(e, "occurrence", None),
                )
            raise

    def _backoff(self, attempt: int) -> None:
        """Jittered exponential backoff before retry ``attempt`` (1-based).
        Jitter draws from a seeded stream so chaos runs replay exactly."""
        base = max(0.0, self._cfg.backoff_base_ms) / 1e3
        cap = max(base, self._cfg.backoff_max_ms / 1e3)
        delay = min(cap, base * (2 ** (attempt - 1)))
        delay *= 0.5 + 0.5 * float(self._retry_rng.rand())
        if delay > 0:
            time.sleep(delay)

    def _retry_transient(
        self, fn: Any, transient: Any = is_transient
    ) -> Any:
        """THE bounded-backoff retry policy for every non-step boundary
        (group ingest, deferred merge, snapshot read): run ``fn`` up to
        ``1 + max_retries`` times, retrying (counted, jitter-backed-off)
        failures ``transient`` accepts, re-raising everything else — one
        implementation, so accounting and seeding can never diverge between
        sites. Step recovery stays in :meth:`_recover_step` (it adds
        rollback and kernel demotion on top of this policy)."""
        attempt = 0
        while True:
            try:
                return fn()
            except BaseException as e:  # noqa: BLE001 - classified by policy
                if not transient(e) or attempt >= self._cfg.max_retries:
                    raise
                attempt += 1
                self._stats.record_retry()
                if self._trace is not None:
                    self._trace.event(
                        "retry", trace=self._group_tid or ENGINE_TRACE, attempt=attempt,
                    )
                self._backoff(attempt)

    def _step_shadow(self) -> Optional[Any]:
        """The donation-aware shadow handoff: the pre-step state a failed
        step rolls back onto. Without donation the live buffers survive the
        call untouched, so the shadow is a free reference; with donation the
        step CONSUMES them, so transactional mode pays one device copy.
        None = not transactional (a failure is sticky, as before ISSUE 6)."""
        if not self._transactional:
            return None
        if not self._donate:
            return self._state
        if self._layout is not None and isinstance(self._state, dict):
            return ArenaLayout.clone_buffers(self._state)
        return jax.tree.map(lambda x: jnp.array(x, copy=True), self._state)

    # ------------------------------------------------------------------- quarantine

    def quarantine(self) -> List[QuarantineRecord]:
        """The dead-letter ledger: batches the screen policy rejected, newest
        ``config.quarantine_capacity`` retained with payloads; lifetime
        counts live in ``stats`` (``quarantined_batches``/``_rows``)."""
        with self._state_lock:
            return list(self._quarantine)

    def clear_quarantine(self) -> None:
        with self._state_lock:
            self._quarantine.clear()

    def _screen_payload(self, item: Any) -> Any:
        """The (args, kwargs) view of one queue item the screen policy sees
        (subclasses strip engine-internal leading arguments)."""
        return item

    def _item_context(self, item: Any) -> Dict[str, Any]:
        """Per-item failure/quarantine context (subclasses add stream ids)."""
        return {}

    def _record_quarantine(self, item: Any, rows: int, cursor: int, reason: str) -> None:
        self._quarantine.append(
            QuarantineRecord(
                cursor=cursor,
                rows=int(rows),
                reason=reason,
                stream_id=self._item_context(item).get("stream_id"),
                payload=item,
            )
        )
        self._stats.quarantined_batches += 1
        self._stats.quarantined_rows += int(rows)
        if self._trace is not None:
            sid = self._item_context(item).get("stream_id")
            extra = {"stream_id": sid} if sid is not None else {}
            self._trace.event(
                "quarantine", trace=self._group_tid or ENGINE_TRACE,
                cursor=int(cursor), rows=int(rows), reason=reason, **extra,
            )

    def _screen_group(
        self, sized: List[Tuple[Any, int]]
    ) -> List[Tuple[Any, int]]:
        """Apply the screen policy per batch BEFORE anything reaches a
        compiled step. Quarantined batches leave the group but their replay
        cursor still advances (``_batches_done`` counts the whole group), so
        kill/resume replay re-screens them identically — the ledger accounts
        for exactly the rejected rows in both runs. ``"error"`` verdicts
        become the sticky dispatcher failure, context attached."""
        policy = self._cfg.screen
        if policy is None:
            return sized
        kept: List[Tuple[Any, int]] = []
        for j, (it, n) in enumerate(sized):
            verdict = None
            if n > 0:
                try:
                    verdict = policy.screen(self._screen_payload(it), n)
                except Exception:  # noqa: BLE001 - a screen probe crash must
                    verdict = None  # not reject what it could not inspect
            if verdict is None:
                kept.append((it, n))
                continue
            action, reason = verdict
            cursor = self._batches_done + j
            if action == "error":
                err = MetricsTPUUserError(f"batch rejected by screen policy: {reason}")
                _attach_ctx(err, cursor=cursor, **self._item_context(it))
                raise err
            self._record_quarantine(it, n, cursor, reason)
        return kept

    # ------------------------------------------------------- degradation ladder

    def _ladder_signals(self) -> Dict[str, float]:
        """The overload detector's feed for one tick. p99 queue residency
        comes from the flight recorder's ``queue_wait_us`` histogram when one
        is attached (the per-batch residency spans — ISSUE 8's definition),
        from the stats ring's windowed ``queue_wait_us`` otherwise; the spill
        rate is pager spill-outs per step over the tick window; queue fill is
        instantaneous."""
        s = self._stats
        # the p99 read is THROTTLED (one refresh per _LADDER_P99_EVERY
        # ticks, memoized between): the recorder-histogram path forces a
        # pending-observation fold and the ring path a windowed sort —
        # neither belongs on EVERY group of the dispatch loop, least of all
        # while overloaded. Watermark tests only need bucket-fresh values.
        self._ladder_ticks += 1
        if self._ladder_p99 is None or self._ladder_ticks % self._LADDER_P99_EVERY == 1:
            p99: Optional[float] = None
            tr = self._trace
            if tr is not None:
                for h in tr.histograms():
                    if h.name == "queue_wait_us":
                        p99 = h.quantile(0.99)
                        break
            if p99 is None:
                from metrics_tpu.engine.stats import _percentile

                waits = sorted(
                    float(r.get("queue_wait_us", 0.0)) for r in s.recent()[-128:]
                )
                p99 = _percentile(waits, 0.99) if waits else 0.0
            self._ladder_p99 = float(p99) if p99 == p99 else 0.0  # NaN-safe
        p99 = self._ladder_p99
        last_steps, last_outs = self._ladder_marks
        dsteps = s.steps - last_steps
        spill_rate = (s.page_outs - last_outs) / dsteps if dsteps > 0 else 0.0
        self._ladder_marks = (s.steps, s.page_outs)
        return {
            "queue_p99_us": p99,
            "spill_rate": float(spill_rate),
            "queue_depth_frac": self._queue.qsize() / max(1, self._cfg.max_queue),
        }

    def _ladder_tick(self) -> None:
        """One ladder evaluation — once per processed group on the dispatcher
        thread, plus on producer-side SHED rejections (the liveness path for
        shed-only traffic), so the whole tick serializes under the ladder
        lock. A transition applies/releases exactly one rung under the state
        lock and is emitted as a ``ladder`` trace event — the deterministic
        record same-seed replay compares."""
        try:
            with self._ladder_lock:
                move = self._ladder.tick(self._ladder_signals())
                if move is None:
                    return
                frm, to = move
                with self._state_lock:
                    if to > frm:
                        self._engage_rung(self._ladder.rung(to))
                    else:
                        self._release_rung(self._ladder.rung(frm))
                self._stats.ladder_transitions += 1
                self._stats.ladder_level = to
            if self._trace is not None:
                self._trace.event(
                    "ladder", trace=ENGINE_TRACE,
                    action="escalate" if to > frm else "deescalate",
                    level=to, rung=self._ladder.rung(max(frm, to)),
                )
        except BaseException as e:  # noqa: BLE001 - surface, don't kill silently
            _attach_ctx(e, cursor=self._batches_done)
            self._error = e

    def _engage_rung(self, rung: str) -> None:
        """Apply one ladder rung (state lock held). Rungs are deliberately
        idempotent and reversible; a rung that does not apply to this engine
        kind (shed without an admission policy, quantize off-mesh) is a
        recorded no-op — the transition event still fires, so the ladder's
        deterministic walk is identical across engine kinds."""
        if rung == "widen_coalesce":
            self._ladder_saved_window = self._cfg.coalesce_window_ms
            self._cfg.coalesce_window_ms = max(
                self._cfg.coalesce_window_ms, self._ladder.widen_window_ms
            )
        elif rung == "quantize_sync":
            self._engage_quantize()
        elif rung == "defer_cold_reads":
            self._defer_cold_reads = True
        elif rung == "shed":
            if self._admission is not None:
                self._admission.shed_lowest(True)

    def _release_rung(self, rung: str) -> None:
        if rung == "widen_coalesce":
            self._cfg.coalesce_window_ms = self._ladder_saved_window
        elif rung == "quantize_sync":
            self._release_quantize()
        elif rung == "defer_cold_reads":
            self._defer_cold_reads = False
            self._result_cache.clear()
        elif rung == "shed":
            if self._admission is not None:
                self._admission.shed_lowest(False)

    def _engage_quantize(self) -> None:
        """The quantize rung: force the blanket ``q8_block`` sync policy for
        ELIGIBLE states (float sum accumulators — counts/cat/min-max always
        stay exact, PR 10's contract) while engaged. Mesh engines only (the
        policy governs the sync bundle) and only from a fully-exact baseline
        — an operator-set policy is never overridden. The policy is a trace
        constant, so engaging REFRESHES the fingerprint and every program
        key: the quantized programs recompile rather than collide."""
        m = self._metric
        if (
            self._cfg.mesh is None
            or not hasattr(m, "set_sync_precision")
            or self._precision_tag != "exact"
            # the at-rest codec's identity (codec_fp in snapshot meta, the
            # stream-shard row codec) is CONSTRUCTION-pinned: engaging a
            # transient policy under compress_payloads would write snapshots
            # a same-config replacement engine refuses — the rung only
            # toggles the WIRE sync, whose identity travels in program keys
            or self._compress
        ):
            return
        m.set_sync_precision("q8_block")
        if m.sync_precision_tag() != "exact":
            self._ladder_quantized = True
            self._refresh_policy_identity()

    def _release_quantize(self) -> None:
        if self._ladder_quantized:
            self._metric.set_sync_precision("exact")
            self._ladder_quantized = False
            self._refresh_policy_identity()

    def _refresh_policy_identity(self) -> None:
        self._precision_tag = self._metric.sync_precision_tag()
        self._metric_fp = metric_fingerprint(self._metric)
        self._program_memo.clear()
        self._payload_split = None
        self._merged_memo = None

    # ------------------------------------------------------------ pane rotation

    def _maybe_rotate_locked(self) -> None:
        """Rotate the pane ring at this batch boundary when the cadence is
        due (dispatcher thread, state lock held). Batch cadence is a pure
        function of the replay cursor — kill/resume replays rotations at the
        same boundaries; time cadence reads the policy's injectable clock
        and advances on a drift-free schedule (``+= pane_seconds`` per
        rotation, so a stalled dispatcher catches up pane by pane)."""
        w = self._window
        if w is None:
            return
        if self._fleet_rotation:
            # a fleet host rotates only when its FleetEngine says so
            # (rotate_pane() at shared-plan pane boundaries) — the local
            # cadence counts owned batches only and would drift per host
            return
        due = w.rotations_due(
            self._batches_done, self._last_rotate_batches,
            self._win_clock(), self._last_rotate_time,
        )
        for _ in range(due):
            self._rotate_once_locked()

    def rotate_pane(self) -> None:
        """Rotate the pane ring NOW, at an externally chosen batch boundary.

        The fleet composition seam (ISSUE 20): a windowed fleet host's
        rotation boundaries are positions of the SHARED ingest plan, not of
        its local (owned-batches-only) replay cursor — the FleetEngine
        flushes and calls this when the global cursor crosses a pane
        boundary, so every host rotates at the same plan-agreed position
        with no clock and no collective (the shared plan IS the agreement).
        The flush first means every batch submitted before the boundary
        folds into the closing pane; the rotation itself is the same
        plan/commit split as the cadence path.
        """
        if self._window is None or self._window.kind == "cumulative":
            raise MetricsTPUUserError(
                "rotate_pane() needs a rotating config.window (tumbling/"
                "sliding/ewma); this engine serves cumulative state"
            )
        self.flush()
        with self._state_lock:
            self._rotate_once_locked()

    def _rotate_once_locked(self) -> None:
        """One pane rotation, PLAN/COMMIT split like the pager (ISSUE 13
        satellite: a transiently-retried rotation must never double-decay).

        Plan: evaluate the closing pane for the drift detector (a pure read
        — ``drift_eval`` transients re-read the same state) and run the
        non-donated rotate/decay program (``pane_rotate`` transients re-run
        against the untouched carry; EWMA's scale applies to the OLD buffers
        each attempt, so exactly one decay ever lands). Commit: swap the
        state, bump the cursor/rotation marks, record once."""
        drift_values: Optional[List[Tuple[Optional[int], Any]]] = None
        # EMPTY panes (a time-cadence catch-up closing panes no batch ever
        # touched, a traffic gap) are NOT drift observations: recording an
        # init-state result would raise false alarms and — under the
        # first/mean baselines — poison the reference forever
        if self._drift is not None and self._batches_done > self._pane_open_cursor:

            def eval_once() -> List[Tuple[Optional[int], Any]]:
                self._fault("drift_eval")
                return self._drift_values_locked()

            drift_values = self._retry_transient(eval_once)
        incoming = (
            (self._pane_cursor + 1) % self._panes if self._window.stacked else 0
        )
        ewma = self._window.kind == "ewma"
        planned = self._plan_rotation(incoming)
        # ---- commit (everything below is infallible bookkeeping)
        self._commit_rotation(planned, incoming)
        self._merged_memo = None
        self._result_cache.clear()
        self._pane_cursor = incoming
        self._rotations += 1
        self._pane_open_cursor = self._batches_done
        if self._window.pane_batches > 0:
            self._last_rotate_batches += self._window.pane_batches
        else:
            self._last_rotate_time += self._window.pane_seconds
        self._stats.record_rotation(
            cursor=self._pane_cursor,
            live=min(self._rotations + 1, self._panes),
            ewma=ewma,
        )
        if self._trace is not None:
            self._trace.event(
                "pane_rotate", trace=ENGINE_TRACE,
                rotation=self._rotations, cursor=self._pane_cursor,
                kind=self._window.kind,
            )
        if drift_values is not None:
            self._record_drift(drift_values)

    def _plan_rotation(self, incoming: int) -> Any:
        """The FALLIBLE half of a rotation: run the non-donated rotate/decay
        program under the ``pane_rotate`` fault site and the bounded
        transient retry. Pure in the carried state — a retried attempt
        re-runs against the untouched carry, so nothing ever decays or
        clears twice. Subclasses with non-device rings (the stream-sharded
        pager) override with their own pure plan."""

        def rotate_once() -> Any:
            self._fault("pane_rotate")
            if self._win_stacked:
                return self._rotate_program()(
                    self._state, jnp.asarray(incoming, jnp.int32)
                )
            return self._decay_program()(self._state)

        return self._retry_transient(rotate_once)

    def _commit_rotation(self, planned: Any, incoming: int) -> None:
        """The infallible half: swap in the planned state."""
        self._state = planned
        self._state_version += 1

    def _drift_values_locked(self) -> List[Tuple[Optional[int], Any]]:
        """The CLOSING pane's result(s) as host values, ``(series_key,
        value)`` pairs — one anonymous series for the base engine; the
        multi-stream engine overrides with one series per stream. Pure read:
        the carried state is not touched (the drift_eval retry contract)."""
        state = self._merged_state() if self._deferred else self._state
        if self._win_stacked:
            value = self._pane_value_program()(
                state, jnp.asarray(self._pane_cursor, jnp.int32)
            )
        else:  # ewma: the decayed accumulation, read BEFORE this decay
            value = self._compute_program()(state)
        return [(None, jax.device_get(value))]

    def _record_drift(self, values: List[Tuple[Optional[int], Any]]) -> None:
        """Commit half of the drift evaluation: record each series exactly
        once (after any plan-phase retries) and surface transitions as
        ``drift_alarm`` trace events + counters."""
        pane = self._rotations - 1  # the pane that just closed, 0-based
        for key, value in values:
            transitions = self._drift.record(value, key=key, pane=pane)
            self._stats.drift_evals += 1
            for a in transitions:
                if a.kind == "raise":
                    self._stats.drift_alarms += 1
                if self._trace is not None:
                    self._trace.event(
                        "drift_alarm", trace=ENGINE_TRACE,
                        kind=a.kind, series=a.name, pane=pane,
                        **({"stream_id": a.key} if a.key is not None else {}),
                    )

    # ---------------------------------------------------------- elastic reshard

    def reshard(
        self,
        *,
        world: Optional[int] = None,
        mesh: Optional[Any] = None,
        resident_streams: Optional[int] = None,
        stream_shard: Optional[bool] = None,
    ) -> Dict[str, Any]:
        """Live elastic resharding: grow/shrink the mesh world (or the
        stream-shard factor) WITHOUT losing state, under traffic.

        Implemented as snapshot-through-the-restore-matrix: drain in-flight
        work, capture the engine's durable form in memory WITH topology
        provenance (the exact document :meth:`snapshot` writes), swap the
        topology (mesh, world, bucket divisor, program identity), and restore
        the captured state through the cross-topology restore matrix — delta
        states merge/embed exactly; ``cat``/scan states refuse loudly across
        worlds (their per-shard capacity buffers have no exact re-shard), and
        a refusal ROLLS BACK to the captured topology, so the engine keeps
        serving as it was. Stream-sharded engines re-home every stream under
        the new ``sid % world`` rule by seeding the new pager's spill store;
        rows fault back in on first touch, bit-exactly.

        Pass ``world=`` (single-axis meshes; devices come from the running
        backend) or an explicit ``mesh=``; stream-sharded engines may also
        change ``resident_streams``. Returns ``{"from_world", "to_world",
        "cursor"}``; the cursor is unchanged — no replay is needed for a
        manual reshard (everything submitted was folded before the drain).
        Also the recovery move behind the ``shard_loss`` fault site (see
        ``config.elastic_min_world``)."""
        self._join_queue()
        with self._state_lock:
            return self._reshard_locked(
                world=world, mesh=mesh, resident_streams=resident_streams,
                stream_shard=stream_shard, auto=False,
            )

    def _reshard_locked(
        self,
        *,
        world: Optional[int] = None,
        mesh: Optional[Any] = None,
        resident_streams: Optional[int] = None,
        stream_shard: Optional[bool] = None,
        auto: bool = False,
    ) -> Dict[str, Any]:
        if stream_shard is not None and bool(stream_shard) != bool(
            getattr(self, "_stream_shard", False)
        ):
            raise MetricsTPUUserError(
                "toggling stream sharding live is not supported: snapshot this "
                "engine and restore into a newly-constructed one with the "
                "desired stream_shard setting"
            )
        if resident_streams is not None and not getattr(self, "_stream_shard", False):
            raise MetricsTPUUserError(
                "resident_streams only applies to stream-sharded engines"
            )
        if resident_streams is not None and int(resident_streams) <= 0:
            raise MetricsTPUUserError(
                f"resident_streams must be positive, got {resident_streams!r}"
            )
        if self._cfg.mesh is None:
            raise MetricsTPUUserError(
                "reshard() needs a mesh engine (a single-device engine has no "
                "topology to change); construct with EngineConfig(mesh=...)"
            )
        new_mesh, new_world = self._target_mesh(world, mesh)
        # bucket divisibility validates BEFORE anything mutates: a bad target
        # world refuses (typed) with the engine untouched
        try:
            new_policy = BucketPolicy(
                self._cfg.buckets, pad_value=self._cfg.pad_value, divisor=new_world
            )
        except ValueError as e:
            raise MetricsTPUUserError(
                f"reshard(world={new_world}) is incompatible with the declared "
                f"buckets {self._cfg.buckets}: {e}"
            ) from e
        old_world = self._world

        def capture() -> Tuple[Any, Dict[str, Any]]:
            self._fault("reshard_snapshot")
            return self._snapshot_doc()

        state, meta = self._retry_transient(capture)
        old_topo = self._topology_state()
        self._apply_topology(new_mesh, new_world, new_policy, resident_streams)

        def commit() -> None:
            self._fault("reshard_restore")
            self._restore_commit(state, meta)

        try:
            self._retry_transient(commit)
        except BaseException:
            # refusals stay loud AND non-destructive: fall back to the
            # captured topology and recommit the same document verbatim —
            # the engine keeps serving exactly as it was
            self._apply_topology_state(old_topo)
            self._restore_commit(state, meta)
            raise
        self._stats.record_reshard(old_world, new_world, self._batches_done, auto)
        if self._trace is not None:
            self._trace.event(
                "reshard", trace=ENGINE_TRACE, from_world=old_world,
                to_world=new_world, cursor=self._batches_done, auto=auto,
            )
        return {
            "from_world": old_world,
            "to_world": new_world,
            "cursor": self._batches_done,
        }

    def _target_mesh(self, world: Optional[int], mesh: Optional[Any]) -> Tuple[Any, int]:
        """Resolve the reshard target: an explicit mesh (must carry the
        engine's axes), or the first ``world`` live devices of the current
        platform on the engine's single axis."""
        if mesh is not None:
            names = set(getattr(mesh, "axis_names", ()))
            missing = [a for a in self._axis_names() if a not in names]
            if missing:
                raise MetricsTPUUserError(
                    f"target mesh lacks the engine's sync axes {missing} "
                    f"(mesh axes: {sorted(names)})"
                )
            w = int(np.prod([mesh.shape[a] for a in self._axis_names()]))
            return mesh, w
        if world is None:
            raise MetricsTPUUserError("reshard() needs world= or mesh=")
        w = int(world)
        if w <= 0:
            raise MetricsTPUUserError(f"world must be positive, got {world!r}")
        axes = self._axis_names()
        if len(axes) != 1:
            raise MetricsTPUUserError(
                "reshard(world=...) supports single-axis meshes; pass an "
                "explicit mesh= for multi-axis topologies"
            )
        from jax.sharding import Mesh

        platform = self._cfg.mesh.devices.flat[0].platform
        devs = [d for d in jax.devices() if d.platform == platform]
        if w > len(devs):
            raise MetricsTPUUserError(
                f"reshard(world={w}) exceeds the {len(devs)} available "
                f"{platform} devices"
            )
        return Mesh(np.asarray(devs[:w]), axes), w

    def _topology_state(self) -> Dict[str, Any]:
        """Everything a reshard rollback must put back (subclasses extend:
        the stream-sharded engine adds its pager/residency)."""
        return {
            "mesh": self._cfg.mesh,
            "world": self._world,
            "policy": self._policy,
            "serialize": self._serialize,
        }

    def _apply_topology_state(self, t: Dict[str, Any]) -> None:
        self._cfg.mesh = t["mesh"]
        self._world = t["world"]
        self._policy = t["policy"]
        self._serialize = t["serialize"]
        self._invalidate_topology_memos()

    def _apply_topology(
        self, mesh: Any, world: int, policy: BucketPolicy,
        resident_streams: Optional[int] = None,
    ) -> None:
        """Swap the live topology (state lock held). The captured snapshot
        doc still describes the OLD topology; ``_restore_commit`` right after
        this is what moves the state across."""
        self._cfg.mesh = mesh
        self._world = world
        self._policy = policy
        self._serialize = (
            mesh.devices.flat[0].platform == "cpu" and not self._deferred
        )
        self._invalidate_topology_memos()

    def _invalidate_topology_memos(self) -> None:
        # every program key embeds the mesh; the merge template and payload
        # accounting embed the world — all of it rebuilds lazily
        self._program_memo.clear()
        self._merged_abs_memo = None
        self._merged_memo = None
        self._payload_split = None

    def _shard_loss_target(self) -> Optional[int]:
        """The world a shard-loss auto-reshard shrinks to: the largest
        bucket-divisor-compatible world strictly below the current one, never
        under ``config.elastic_min_world`` (0 disarms). None = go sticky."""
        lo = int(self._cfg.elastic_min_world)
        if lo <= 0 or self._cfg.mesh is None:
            return None
        for w in range(self._world - 1, lo - 1, -1):
            if all(b % w == 0 for b in self._cfg.buckets):
                return w
        return None

    # -------------------------------------------------------------------- processing

    def _process_group(
        self,
        group: List[Any],
        queue_wait_us: float,
        tids: Optional[List[Tuple[str, float]]] = None,
    ) -> None:
        with self._state_lock:
            # only INGEST faults retry at this level: they fire before
            # anything folds, so the whole group re-runs from untouched
            # state; everything else is handled deeper or goes sticky
            ingest_transient = lambda e: (  # noqa: E731 - local policy closure
                isinstance(e, InjectedFault) and e.site == "ingest" and e.transient
            )
            tr = self._trace
            if tr is None:
                self._retry_transient(
                    lambda: self._process_group_locked(group, queue_wait_us),
                    transient=ingest_transient,
                )
                return
            # the megabatch ("coalesce") span: its trace id derives from the
            # first absorbed submit, and its links are ALL of them — the
            # causal record a tail-latency investigation walks backwards
            links = [t for t, _ in tids or ()]
            waits = [w for _, w in tids or ()]
            gid = TraceRecorder.group_trace(links)
            self._group_tid = gid
            # the group's queue_wait is the LONGEST member residency (members
            # wait concurrently, so that is the wall-clock the tail paid); the
            # histogram sees every member, the per-batch distribution
            tr.complete("queue_wait", trace=gid, dur_us=max(waits, default=0.0))
            for w in waits:
                tr.observe("queue_wait_us", w)
            handle = tr.begin(
                "coalesce", trace=gid, links=links, batches=len(group),
                **self._group_context(group),
            )
            try:
                self._retry_transient(
                    lambda: self._process_group_locked(group, queue_wait_us),
                    transient=ingest_transient,
                )
            finally:
                self._group_tid = None
                tr.end(handle)

    def _latch_payload(self, merged: Any) -> Tuple[Tuple[Any, ...], Dict[str, Any]]:
        """The (args, kwargs) a host-attr latch row is sliced from (subclasses
        strip engine-internal leading arguments, e.g. stream ids)."""
        return merged

    def _latch_host_attrs(self, merged: Any) -> None:
        """Latch host-derived compute attrs (``Metric.host_compute_attrs``)
        from live data with ONE eager 1-row update, BEFORE any program key is
        built. The latched values are trace constants, so they must be part of
        every program's identity: without this, two engines sharing an
        ``AotCache`` but serving different input modes would collide on a
        compute program with the WRONG constant baked in (same fingerprint,
        same state signature, silently wrong value) — and a fully warm engine
        (every program a cache hit, nothing ever traced) would never latch at
        all. The eager row's state delta is discarded; only the facade's
        attrs (and the refreshed fingerprint) survive."""
        args, kwargs = self._latch_payload(merged)
        n = self._item_rows((args, kwargs))
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        row = [leaf[:1] if is_batch_leaf(leaf, n) else leaf for leaf in leaves]
        a, kw = jax.tree_util.tree_unflatten(treedef, row)
        # a failing latch row leaves the latch ARMED: the raise becomes the
        # sticky dispatcher error, and the first good batch after recovery
        # (reset) latches properly — consuming the latch on failure would
        # bake the unlatched fingerprint into every later program key
        self._metric.update_state(self._metric.init_state(), *a, **kw)
        self._needs_attr_latch = False
        self._metric_fp = metric_fingerprint(self._metric)
        self._program_memo.clear()

    def _process_group_locked(self, group: List[Any], queue_wait_us: float) -> None:
        # a FATAL fault here models the dispatcher dying outright (host OOM,
        # runtime abort): _run exits without draining — the wedge that
        # submit(timeout=)'s sticky raise and _join_queue exist for
        self._fault("dispatcher_kill")
        if self._window is not None and self._window.pane_seconds > 0:
            # TIME-cadence panes rotate BEFORE the group folds: a batch that
            # arrives after the pane's deadline belongs to the NEW pane
            # (batch-cadence panes rotate after the boundary group below —
            # the boundary batch completes its pane)
            self._maybe_rotate_locked()
        self._fault("ingest")  # host ingestion boundary: nothing folded yet
        # size each item ONCE; the sizes feed the empty filter, the screen,
        # the merge's concat, the chunker, and the coalesce telemetry
        sized = [(it, self._item_rows(it)) for it in group]
        kept = self._screen_group(sized)
        nonempty = [(it, n) for it, n in kept if n > 0]
        merged = self._merge_sized(nonempty)
        # an empty group (zero-row tail batches) is a no-op, not a poison
        # pill — it contributes no steps but still advances the replay cursor
        if merged is not None:
            if self._needs_attr_latch:
                self._latch_host_attrs(merged)
            n = sum(rows for _, rows in nonempty)
            try:
                self._execute_payload(merged, int(n), len(nonempty), queue_wait_us)
            except BaseException as e:  # noqa: BLE001 - classified below
                # megabatch shrink-on-retry: when the failed group carried
                # several batches and NO chunk committed, re-dispatch them
                # one at a time — good traffic lands and the sticky error
                # names exactly the poisoned member's cursor. Requires the
                # transactional shadow: without it a donating step may have
                # consumed the carried buffers, and re-dispatching against
                # them would turn the real error into a deleted-array crash.
                # (With partial commits, splitting would double-fold the
                # committed rows — the failure stays group-level sticky.)
                if (
                    len(nonempty) <= 1
                    or getattr(e, "_committed_chunks", 1) != 0
                    or not self._transactional
                ):
                    raise
                self._stats.coalesce_shrinks += 1
                cursors = {id(it): self._batches_done + j for j, (it, _) in enumerate(sized)}
                for it, n_it in nonempty:
                    single = self._merge_sized([(it, n_it)])
                    try:
                        self._execute_payload(single, int(n_it), 1, 0.0)
                    except BaseException as se:  # noqa: BLE001
                        _attach_ctx(
                            se, cursor=cursors.get(id(it)), **self._item_context(it)
                        )
                        raise
        self._batches_done += len(group)
        if self._window is not None and self._window.pane_batches > 0:
            # rotate BEFORE the snapshot cadence: a boundary snapshot then
            # carries the post-rotation ring (cursor + marks in meta), so a
            # restored engine never re-rotates the same boundary
            self._maybe_rotate_locked()
        if (
            self._cfg.snapshot_every > 0
            and self._batches_done % self._cfg.snapshot_every == 0
        ):
            jax.block_until_ready(self._state)
            try:
                self._save_snapshot()
            except BaseException:  # noqa: BLE001 - counted, never sticky
                # a failed PERIODIC snapshot must not take serving down: the
                # accumulated state is intact and the previous generation
                # still backs restore(); count it and keep folding traffic
                self._stats.snapshot_failures += 1

    def _execute_payload(
        self, merged: Tuple[Tuple[Any, ...], Dict[str, Any]], n: int,
        n_coalesced: int, queue_wait_us: float,
    ) -> None:
        """Run one merged (args, kwargs) payload through its bucketed chunks.
        Tags escaping exceptions with ``_committed_chunks`` so the caller
        knows whether a shrink re-dispatch is exactness-safe."""
        args, kwargs = merged
        committed = 0
        try:
            first_chunk = True
            for start, stop, bucket in self._policy.chunks(int(n)):
                while True:
                    try:
                        self._execute_chunk(
                            args, kwargs, start, stop, bucket,
                            n_coalesced if first_chunk else 1,
                            queue_wait_us if first_chunk else 0.0,
                        )
                        break
                    except InjectedFault as e:  # noqa: PERF203 - recovery path
                        target = (
                            self._shard_loss_target()
                            if e.site == "shard_loss" and not e.transient
                            else None
                        )
                        if target is None:
                            raise
                        # a dead shard becomes a smaller world: the fault
                        # fires BEFORE the step executes (nothing folded),
                        # the carried state crosses through the restore
                        # matrix, and THIS chunk re-pads and re-runs on the
                        # surviving topology (the bucket set is unchanged —
                        # _shard_loss_target guarantees divisibility)
                        self._reshard_locked(world=target, auto=True)
                committed += 1
                first_chunk = False
        except BaseException as e:  # noqa: BLE001
            try:
                # ACCUMULATE (don't overwrite): a shard-loss re-dispatch may
                # nest one _execute_* inside another — the shrink-on-retry
                # exactness gate needs the TOTAL committed count
                e._committed_chunks = getattr(e, "_committed_chunks", 0) + committed
            except Exception:  # noqa: BLE001 - exotic exception without a dict
                pass
            raise

    def _execute_chunk(
        self, args: Tuple[Any, ...], kwargs: Dict[str, Any],
        start: int, stop: int, bucket: int, n_coalesced: int, queue_wait_us: float,
    ) -> None:
        """One padded device step: slice+pad the chunk, then hand the padded
        payload to :meth:`_run_padded_step` (shared with the stream-sharded
        routed path, which builds its padded payloads itself)."""
        t0 = time.perf_counter()
        a, kw, mask = self._policy.pad_chunk(args, kwargs, start, stop, bucket)
        self._run_padded_step(
            a, kw, mask, bucket, stop - start, n_coalesced, queue_wait_us, t0
        )

    def _run_padded_step(
        self, a: Tuple[Any, ...], kw: Dict[str, Any], mask: np.ndarray,
        bucket: int, valid: int, n_coalesced: int, queue_wait_us: float, t0: float,
    ) -> None:
        """Run one ALREADY-PADDED payload transactionally: capture the shadow,
        run, commit on success; on failure roll back and let
        :meth:`_recover_step` decide between retry (transient/backoff), kernel
        demotion, and sticky. Upload happens once — retries reuse the uploaded
        payload. ``t0`` is when pad/route work on this payload began, so the
        recorded ``pad`` span covers the caller's host-side build too."""
        if self._win_stacked:
            # the RUNTIME pane index leads the payload: a 0-d int32 ARRAY (a
            # python int would bake into the trace and every rotation would
            # recompile), replicated under a mesh like any broadcast leaf,
            # and shape-stable in the payload signature — the program memo
            # never misses on a pane bump
            a = (np.asarray(self._pane_cursor, np.int32),) + tuple(a)
        t_pad = time.perf_counter()
        payload, mask_dev = self._upload((a, kw), mask)
        ingest_us = (time.perf_counter() - t0) * 1e6  # pad+upload only, not compile
        tr = self._trace
        if tr is not None:
            tr.complete(
                "pad", trace=self._group_tid or ENGINE_TRACE,
                dur_us=(t_pad - t0) * 1e6, bucket=bucket, rows=valid,
            )
        attempt = 0
        while True:
            shadow = self._step_shadow()
            try:
                self._do_step(
                    payload, mask, mask_dev, bucket, valid,
                    n_coalesced, queue_wait_us, ingest_us, t0, t_pad,
                )
                return
            except BaseException as e:  # noqa: BLE001 - classified in recovery
                if not self._recover_step(e, shadow, attempt):
                    _attach_ctx(e, step=self._step, bucket=bucket)
                    raise
                attempt += 1

    def _do_step(
        self, payload: Any, mask: np.ndarray, mask_dev: Any, bucket: int,
        valid: int, n_coalesced: int, queue_wait_us: float, ingest_us: float,
        t0: float, t_pad: float,
    ) -> None:
        tr = self._trace
        gid = self._group_tid or ENGINE_TRACE
        self._fault("compile")
        if self._kernel_tag() != "xla":
            # the kernel site models a runtime kernel-backend failure —
            # meaningless for an engine already on the reference lowering
            self._fault("kernel")
        if self._cfg.mesh is not None:
            # a shard dying is only meaningful on a mesh; consulted BEFORE
            # the step executes, so nothing has folded when it fires — a
            # non-transient loss retries the chunk on the SURVIVING world
            # (auto-reshard, config.elastic_min_world) with zero rollback debt
            self._fault("shard_loss")
        if tr is None:
            program = self._update_program(payload, mask)
        else:
            # AOT lookup span: hit vs compile, attributed by _update_program
            # itself (exact under a shared AotCache, where a miss-counter
            # delta would blame another engine's concurrent compile on us)
            aot_handle = tr.begin("aot", trace=gid, bucket=bucket)
            program = self._update_program(payload, mask)
            tr.end(aot_handle, cache=self._last_aot_outcome)
        depth = self._queue.qsize()
        step_handle = (
            tr.begin("device_step", trace=gid, step=self._step, bucket=bucket, valid=valid)
            if tr is not None
            else None
        )
        new_state, token = program(self._state, payload, mask_dev)
        # the strictest injection point: device work dispatched, host commit
        # pending — recovery MUST discard new_state, not fold it twice
        self._fault("step")
        sync_us: Optional[float] = None
        if self._watchdog_enabled:
            # watchdog mode syncs BEFORE commit (trading the async pipeline
            # for containment): an expiry rolls back cleanly — the hung op
            # keeps its buffers, the engine keeps its shadow
            self._fault("watchdog")
            t_sync = time.perf_counter()
            if self._cfg.step_timeout_s > 0:
                wait_with_timeout(
                    lambda: jax.block_until_ready(token), self._cfg.step_timeout_s
                )
            else:
                jax.block_until_ready(token)
            sync_us = (time.perf_counter() - t_sync) * 1e6
            self._inflight.clear()
            if tr is not None:
                tr.complete("watchdog_sync", trace=gid, dur_us=sync_us)
        self._state = new_state
        self._state_version += 1
        self._step += 1
        if not self._watchdog_enabled:
            sync_us = self._bound_inflight(token)
            if sync_us is not None and tr is not None:
                tr.complete("inflight_sync", trace=gid, dur_us=sync_us)
        wall_us = (time.perf_counter() - t0) * 1e6
        if step_handle is not None:
            tr.end(step_handle)
            tr.observe("step_latency_us", wall_us)
        self._stats.record_step(
            bucket=bucket, valid=valid, queue_depth=depth,
            ingest_us=ingest_us, sync_us=sync_us,
            pad_us=(t_pad - t0) * 1e6,
            queue_wait_us=queue_wait_us,
            wall_us=wall_us,
            coalesced=n_coalesced,
        )
        if self._cfg.mesh is not None and not self._deferred:
            # step-sync pays the fused bundle INSIDE every step — count the
            # payload per step (deferred counts per boundary merge instead)
            self._stats.record_sync_payload(*self._sync_payload_split())

    def _recover_step(self, e: BaseException, shadow: Optional[Any], attempt: int) -> bool:
        """Classify a step failure and perform its recovery action. True =
        the chunk should retry (state already rolled back); False = let it
        become the sticky dispatcher error."""
        if shadow is None:
            # donation without transactional mode: the buffers may already be
            # consumed — nothing safe to roll back onto (pre-ISSUE-6 behavior)
            return False
        # pre-step rollback: the shadow IS the pre-step state (a reference
        # when donation is off, a retained copy when on); any new_state the
        # failed attempt produced is discarded, so nothing folds twice
        self._state = shadow
        self._merged_memo = None
        self._stats.rollbacks += 1
        tr = self._trace
        if tr is not None:
            tr.event(
                "rollback", trace=self._group_tid or ENGINE_TRACE,
                cause=type(e).__name__,
            )
        if isinstance(e, StepTimeoutError):
            self._stats.watchdog_timeouts += 1
        if (
            isinstance(e, InjectedFault)
            and e.site == "kernel"
            and self._cfg.degrade_kernel
            and self._kernel_tag() != "xla"
        ):
            # graceful degradation: the kernel backend failed at dispatch —
            # demote this engine to the XLA reference lowering and rebuild.
            # The resolved backend tag is part of every program key, so the
            # demoted programs recompile rather than collide in a shared
            # cache; demotion is one-way for the engine's lifetime.
            self._kernel_backend = "xla"
            self._program_memo.clear()
            self._stats.kernel_demotions += 1
            if tr is not None:
                tr.event(
                    "kernel_demotion", trace=self._group_tid or ENGINE_TRACE,
                    backend="xla",
                )
            return True
        if not is_transient(e) or attempt >= self._cfg.max_retries:
            return False
        self._stats.record_retry()
        if tr is not None:
            tr.event(
                "retry", trace=self._group_tid or ENGINE_TRACE, attempt=attempt + 1,
            )
        self._backoff(attempt + 1)
        return True

    def _upload(self, payload: Any, mask: np.ndarray) -> Tuple[Any, Any]:
        """Host → device transfer with the step program's expected shardings."""
        if self._cfg.mesh is None:
            # uncommitted numpy feeds the executable directly (default device)
            return payload, mask
        batch_sh = self._batch_sharding()
        rep_sh = self._replicated_sharding()
        n_rows = mask.shape[0]
        payload = jax.tree.map(
            lambda x: jax.device_put(x, batch_sh if is_batch_leaf(x, n_rows) else rep_sh)
            if isinstance(x, (np.ndarray, jnp.ndarray))
            else x,
            payload,
        )
        return payload, jax.device_put(mask, batch_sh)

    def _bound_inflight(self, token: Any) -> Optional[float]:
        """Enforce the double-buffering depth via step tokens; returns the
        observed sync µs when the dispatcher had to block."""
        self._inflight.append(token)
        if self._serialize:
            t0 = time.perf_counter()
            jax.block_until_ready(token)
            self._inflight.clear()
            return (time.perf_counter() - t0) * 1e6
        if len(self._inflight) <= max(1, self._cfg.in_flight):
            return None
        oldest = self._inflight.popleft()
        t0 = time.perf_counter()
        jax.block_until_ready(oldest)
        return (time.perf_counter() - t0) * 1e6


def _attach_ctx(exc: BaseException, **kv: Any) -> None:
    """Tag an exception with engine failure context (batch cursor, bucket,
    stream ids) without changing its type mid-flight; ``_raise_if_failed``
    folds the tags into the producer-facing :class:`EngineDispatchError`.
    ``setdefault`` keeps the INNERMOST (most precise) value when several
    layers tag the same key on the way out."""
    ctx = getattr(exc, "_engine_ctx", None)
    if ctx is None:
        try:
            exc._engine_ctx = ctx = {}
        except Exception:  # noqa: BLE001 - exceptions with __slots__
            return
    for k, v in kv.items():
        if v is not None and (not isinstance(v, (list, tuple)) or len(v)):
            ctx.setdefault(k, v)


def _aux_leaves_equal(a: Any, b: Any) -> bool:
    """Equality for non-batch (broadcast/config) leaves, cheap and safe:
    unequal-on-doubt so an uncertain comparison costs one un-coalesced step,
    never a wrong result."""
    if a is b:
        return True
    try:
        if isinstance(a, (np.ndarray, jnp.ndarray)) or isinstance(b, (np.ndarray, jnp.ndarray)):
            # reject on metadata BEFORE materializing anything: np.asarray of
            # a large (or device-resident) aux leaf would cost more than the
            # dispatch the merge saves
            a_shape, b_shape = getattr(a, "shape", None), getattr(b, "shape", None)
            a_dtype, b_dtype = getattr(a, "dtype", None), getattr(b, "dtype", None)
            if a_shape != b_shape or a_dtype != b_dtype:
                return False
            if int(np.prod(a_shape)) > _COALESCE_AUX_COMPARE_CAP:
                return False
            return bool(np.array_equal(np.asarray(a), np.asarray(b)))
        return bool(a == b)
    except Exception:  # noqa: BLE001 - any exotic leaf: just don't coalesce
        return False


def _mesh_step_unsupported_reason(metric: Any) -> Optional[str]:
    """STEP-SYNC mesh steps merge per-shard DELTAS (masked update from a fresh
    state, psum-synced, merged into the carry) — exact for delta/custom masked
    strategies, but NOT for scan-fallback members, whose states (e.g. the
    static-capacity curve buffers) do not merge by their reduction per step.
    Deferred sync (``mesh_sync="deferred"``) has no such restriction: shards
    fold their own rows into shard-local state and the boundary merge
    all-gathers the buffers."""
    strategies = (
        metric.masked_update_strategies()
        if hasattr(metric, "masked_update_strategies")
        else {type(metric).__name__: metric.masked_update_strategy()}
    )
    for name, s in strategies.items():
        if s == "scan":
            return (
                f"member {name!r} needs the sequential masked fallback, which has no "
                "exact step-sync mesh (shard-and-merge) form; serve it on a single "
                "device or under EngineConfig(mesh_sync='deferred')"
            )
    return None
