"""The streaming engine: bounded ingest queue → padded buckets → AOT steps.

Dataflow (one engine = one metric/collection served as a stream consumer)::

    submit(*batch)        # producer thread(s); BLOCKS when the queue is full
      └─ bounded queue (backpressure, config.max_queue batches)
           └─ dispatcher thread: chunk → pad to bucket (host numpy) →
              device upload → AOT-compiled step(state, batch, mask)
                 └─ donated state buffers, up to config.in_flight steps
                    un-synced (JAX async dispatch overlaps the host's padding
                    of batch k+1 with the device's execution of batch k)
    result()              # flush + AOT-compiled compute on the final state

Design notes:

* **Closed program set.** Every step program is keyed by (bucket signature,
  metric fingerprint, mesh, donation, backend) and compiled ahead-of-time via
  ``jit(...).lower(...).compile()`` — after at most ``len(buckets)`` compiles
  per input signature the engine never traces again (``engine/aot.py``).
* **Donation.** The state pytree is donated into each step: XLA merges the
  delta in place instead of allocating a second state copy (material for
  big-state metrics; ``metric.py`` documents the same policy for compiled
  forward). Donation is skipped on CPU, which doesn't implement it.
* **Mesh-aware steps.** With ``config.mesh`` the step runs under ``shard_map``:
  batch rows and mask shard over ``config.axis``, state stays replicated, the
  per-shard masked delta is psum-merged in-step (``sync_states``) so the
  carried state is always the GLOBAL state — compute needs no further sync,
  and a snapshot taken between any two steps is globally consistent.
* **Virtual-mesh serialization.** On CPU meshes overlapping async collective
  executions can deadlock the in-process communicator
  (``parallel/embedded.py``); the engine serializes steps there. Real TPU
  meshes keep the full ``in_flight`` pipeline.
* **Recovery.** ``snapshot_every > 0`` writes crash-safe periodic snapshots
  (``engine/snapshot.py``); ``restore()`` resumes exactly — replaying the
  stream from the snapshot's step reproduces the uninterrupted result.
"""
import queue
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.engine.aot import AotCache, metric_fingerprint
from metrics_tpu.engine.bucketing import BucketPolicy
from metrics_tpu.engine.snapshot import load_snapshot, save_snapshot
from metrics_tpu.engine.stats import EngineStats
from metrics_tpu.utils.data import infer_batch_size, is_batch_leaf
from metrics_tpu.utils.exceptions import MetricsTPUUserError

__all__ = ["EngineConfig", "StreamingEngine"]

_STOP = object()


@dataclass
class EngineConfig:
    """Configuration for :class:`StreamingEngine`.

    Args:
        buckets: allowed padded batch sizes (the closed shape set).
        max_queue: bounded ingest queue capacity, in batches. ``submit``
            blocks when full — backpressure to the producer.
        in_flight: device steps allowed un-synced before the dispatcher
            blocks on the oldest (double-buffering depth).
        snapshot_every: BATCHES between crash-safe state snapshots (0 = off).
            Snapshots land on batch boundaries only — a batch larger than the
            top bucket spans several device steps, and a mid-batch snapshot
            would break batch-level replay on resume.
        snapshot_dir: where snapshots live (required when snapshot_every > 0).
        compilation_cache_dir: JAX persistent compilation cache directory —
            warm process restarts skip XLA compiles entirely.
        mesh: optional ``jax.sharding.Mesh`` for sharded engine steps.
        axis: mesh axis name carrying the batch shards.
        donate: donate state buffers into each step (ignored on CPU).
        pad_value: fill for pad rows (must pass the metric's input checks;
            masked out of every reduction regardless).
        telemetry_capacity: ring-buffer size for per-step telemetry.
        snapshot_keep: complete snapshots retained after GC.
    """

    buckets: Tuple[int, ...] = (256, 1024)
    max_queue: int = 64
    in_flight: int = 2
    snapshot_every: int = 0
    snapshot_dir: Optional[str] = None
    compilation_cache_dir: Optional[str] = None
    mesh: Optional[Any] = None
    axis: str = "dp"
    donate: bool = True
    pad_value: Any = 0
    telemetry_capacity: int = 1024
    snapshot_keep: int = 2


class StreamingEngine:
    """Drive a ``Metric``/``MetricCollection`` as a streaming service.

    Thread model: producers call :meth:`submit`; one dispatcher thread owns
    the device pipeline; :meth:`flush`/:meth:`result`/:meth:`state` join the
    queue before touching state, so reads never race the dispatcher.
    """

    def __init__(self, metric: Any, config: Optional[EngineConfig] = None, aot_cache: Optional[AotCache] = None):
        self._metric = metric
        self._cfg = config or EngineConfig()
        reason = metric.masked_update_unsupported_reason()
        if reason is not None:
            raise MetricsTPUUserError(
                f"metric cannot be served by the streaming engine: {reason}"
            )
        divisor = 1
        if self._cfg.mesh is not None:
            divisor = int(np.prod([self._cfg.mesh.shape[a] for a in self._axis_names()]))
        self._policy = BucketPolicy(self._cfg.buckets, pad_value=self._cfg.pad_value, divisor=divisor)
        self._aot = aot_cache if aot_cache is not None else AotCache(self._cfg.compilation_cache_dir)
        self._stats = EngineStats(self._cfg.telemetry_capacity)
        self._metric_fp = metric_fingerprint(metric)
        if self._cfg.snapshot_every > 0 and not self._cfg.snapshot_dir:
            raise MetricsTPUUserError("snapshot_every > 0 requires snapshot_dir")
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(1, self._cfg.max_queue))
        self._program_memo: Dict[Tuple, Any] = {}
        self._inflight: "deque" = deque()
        self._worker: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._step = 0
        self._batches_done = 0
        self._state = self._put_state(metric.init_state())
        self._donate = bool(self._cfg.donate) and jax.default_backend() != "cpu"
        self._serialize = (
            self._cfg.mesh is not None and self._cfg.mesh.devices.flat[0].platform == "cpu"
        )

    # ------------------------------------------------------------------ mesh helpers

    def _axis_names(self) -> Tuple[str, ...]:
        a = self._cfg.axis
        return tuple(a) if isinstance(a, (tuple, list)) else (a,)

    def _replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self._cfg.mesh, P())

    def _batch_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self._cfg.mesh, P(self._cfg.axis))

    def _put_state(self, state: Any) -> Any:
        """Device-commit a state pytree (replicated over the mesh, if any)."""
        if self._cfg.mesh is None:
            return jax.tree.map(jnp.asarray, state)
        rep = self._replicated_sharding()
        return jax.tree.map(lambda x: jax.device_put(jnp.asarray(x), rep), state)

    def _abstract_state(self) -> Any:
        abs_state = self._metric.abstract_state()
        if self._cfg.mesh is None:
            return abs_state
        rep = self._replicated_sharding()
        return jax.tree.map(lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=rep), abs_state)

    # ------------------------------------------------------------------ AOT programs

    def _update_program(self, payload: Any, mask: np.ndarray):
        """The compiled step for this payload signature (AOT, cached).

        Hot path: a per-engine memo keyed by the concrete payload signature
        (one tree_flatten) skips the abstract-tree construction and the full
        structural program key on every steady-state step.
        """
        memo_key = (AotCache.signature_of(payload), mask.shape)
        prog = self._program_memo.get(memo_key)
        if prog is not None:
            self._aot.count_hit()  # memo short-circuit still counts as a cache hit
            return prog
        payload_abs = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)
            if isinstance(x, (np.ndarray, jnp.ndarray))
            else x,
            payload,
        )
        mask_abs = jax.ShapeDtypeStruct(mask.shape, np.dtype(bool))
        key = self._aot.program_key(
            "update", self._metric_fp, arg_tree=(payload_abs, mask_abs),
            mesh=self._cfg.mesh, donate=self._donate,
        )
        prog = self._aot.get_or_compile(
            key, lambda: self._build_update_program(payload_abs, mask_abs)
        )
        self._program_memo[memo_key] = prog
        return prog

    def _build_update_program(self, payload_abs: Any, mask_abs: Any):
        """Compile ``(state, payload, mask) -> (new_state, token)``.

        ``token`` is the step's global valid-row count — a tiny NON-donated
        output the dispatcher can block on to bound in-flight depth (the state
        itself may already have been donated into the NEXT step by the time
        the dispatcher needs to wait, and a donated buffer cannot be synced
        on). It doubles as a liveness cross-check in telemetry.
        """
        metric = self._metric
        mesh, axis = self._cfg.mesh, self._cfg.axis

        if mesh is None:
            def step(state, payload, mask):
                a, kw = payload
                new_state = metric.update_state_masked(state, *a, mask=mask, **kw)
                return new_state, jnp.sum(mask.astype(jnp.int32))

            jitted = jax.jit(step, donate_argnums=(0,) if self._donate else ())
            return jitted.lower(self._abstract_state(), payload_abs, mask_abs).compile()

        from metrics_tpu.parallel.embedded import sharded_masked_step

        sharded = sharded_masked_step(metric, mesh, axis, payload_abs, mask_abs)
        jitted = jax.jit(sharded, donate_argnums=(0,) if self._donate else ())
        n_rows = mask_abs.shape[0]
        batch_sh = self._batch_sharding()
        rep_sh = self._replicated_sharding()
        mask_sharded = jax.ShapeDtypeStruct(mask_abs.shape, mask_abs.dtype, sharding=batch_sh)
        payload_abs = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(
                s.shape, s.dtype,
                sharding=batch_sh if is_batch_leaf(s, n_rows) else rep_sh,
            )
            if hasattr(s, "shape")
            else s,
            payload_abs,
        )
        return jitted.lower(self._abstract_state(), payload_abs, mask_sharded).compile()

    def _compute_program(self):
        key = self._aot.program_key(
            "compute", self._metric_fp, arg_tree=self._metric.abstract_state(),
            mesh=self._cfg.mesh, donate=False,
        )
        metric = self._metric
        return self._aot.get_or_compile(
            key, lambda: jax.jit(metric.compute_from).lower(self._abstract_state()).compile()
        )

    # --------------------------------------------------------------------- lifecycle

    def start(self) -> "StreamingEngine":
        if self._worker is None:
            self._worker = threading.Thread(
                target=self._run, name="metrics-tpu-engine", daemon=True
            )
            self._worker.start()
        return self

    def __enter__(self) -> "StreamingEngine":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.stop()
        if exc_type is None:
            self._raise_if_failed()
        return False

    def stop(self) -> None:
        """Drain the queue and stop the dispatcher (idempotent)."""
        if self._worker is not None:
            self._queue.put(_STOP)
            self._worker.join()
            self._worker = None

    def _raise_if_failed(self) -> None:
        if self._error is not None:
            raise RuntimeError("streaming engine dispatcher failed") from self._error

    # --------------------------------------------------------------------- producers

    def submit(self, *args: Any, **kwargs: Any) -> None:
        """Enqueue one (ragged) batch. Blocks when the queue is full."""
        self._raise_if_failed()
        self.start()
        self._stats.batches_submitted += 1
        self._queue.put((args, kwargs))

    def flush(self) -> None:
        """Block until every submitted batch is folded into the state."""
        self._raise_if_failed()
        self._queue.join()
        jax.block_until_ready(self._state)
        self._raise_if_failed()

    def result(self) -> Any:
        """Flush, then run the AOT-compiled compute on the accumulated state."""
        self.flush()
        return self._compute_program()(self._state)

    def state(self) -> Any:
        """A defensive copy of the accumulated (global) state pytree, after a
        flush. Copied because the live buffers are DONATED into the next
        update step — a borrowed reference would read as deleted after the
        caller submits more traffic."""
        self.flush()
        return jax.tree.map(lambda x: jnp.array(x, copy=True), self._state)

    @property
    def steps(self) -> int:
        return self._step

    @property
    def stats(self) -> EngineStats:
        return self._stats

    @property
    def aot_cache(self) -> AotCache:
        return self._aot

    def telemetry(self) -> Dict[str, Any]:
        return self._stats.summary(self._aot.stats())

    def export_telemetry(self, path: str) -> None:
        self._stats.export(path, self._aot.stats())

    def reset(self) -> None:
        """Fresh accumulation (flushes first); compiled programs are kept."""
        self.flush()
        self._state = self._put_state(self._metric.init_state())
        self._step = 0
        self._batches_done = 0

    # ---------------------------------------------------------------------- recovery

    def snapshot(self) -> str:
        """Flush and write one crash-safe snapshot now."""
        if not self._cfg.snapshot_dir:
            raise MetricsTPUUserError("snapshot() requires config.snapshot_dir")
        self.flush()
        return self._save_snapshot()

    def _save_snapshot(self) -> str:
        host_state = jax.device_get(self._state)
        path = save_snapshot(
            self._cfg.snapshot_dir,
            host_state,
            {
                "step": self._step,
                "batches_done": self._batches_done,
                "rows_in": self._stats.rows_in,
                "rows_padded": self._stats.rows_padded,
            },
            keep=self._cfg.snapshot_keep,
        )
        self._stats.snapshots += 1
        return path

    def restore(self, directory_or_path: Optional[str] = None) -> Dict[str, Any]:
        """Resume from the newest complete snapshot (engine must be idle).

        Returns the snapshot's meta dict — ``batches_done`` is the replay
        cursor: re-submit the stream from that batch onward and the final
        result is exactly the uninterrupted one.
        """
        self.flush()
        state, meta = load_snapshot(directory_or_path or self._cfg.snapshot_dir)
        self._state = self._put_state(state)
        self._step = int(meta.get("step", 0))
        self._batches_done = int(meta.get("batches_done", self._step))
        self._stats.rows_in = int(meta.get("rows_in", self._stats.rows_in))
        self._stats.rows_padded = int(meta.get("rows_padded", self._stats.rows_padded))
        self._stats.resumes += 1
        return meta

    # -------------------------------------------------------------------- dispatcher

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is _STOP:
                    return
                if self._error is None:  # after a failure: drain without work
                    self._process(*item)
            except BaseException as e:  # noqa: BLE001 - surfaced via _raise_if_failed
                self._error = e
            finally:
                self._queue.task_done()

    def _process(self, args: Tuple[Any, ...], kwargs: Dict[str, Any]) -> None:
        n = infer_batch_size((args, kwargs))  # same inference pad_chunk uses
        if n is None:
            raise MetricsTPUUserError("submit() needs at least one array argument with a batch dimension")
        # an empty tail batch is a no-op, not a poison pill — it contributes no
        # steps but still advances the replay cursor (and snapshot cadence)
        for start, stop, bucket in self._policy.chunks(int(n)) if n else []:
            t0 = time.perf_counter()
            a, kw, mask = self._policy.pad_chunk(args, kwargs, start, stop, bucket)
            payload, mask_dev = self._upload((a, kw), mask)
            ingest_us = (time.perf_counter() - t0) * 1e6  # pad+upload only, not compile
            program = self._update_program(payload, mask)
            depth = self._queue.qsize()
            new_state, token = program(self._state, payload, mask_dev)
            self._state = new_state
            self._step += 1
            sync_us = self._bound_inflight(token)
            self._stats.record_step(
                bucket=bucket, valid=stop - start, queue_depth=depth,
                ingest_us=ingest_us, sync_us=sync_us,
            )
        self._batches_done += 1
        if (
            self._cfg.snapshot_every > 0
            and self._batches_done % self._cfg.snapshot_every == 0
        ):
            jax.block_until_ready(self._state)
            self._save_snapshot()

    def _upload(self, payload: Any, mask: np.ndarray) -> Tuple[Any, Any]:
        """Host → device transfer with the step program's expected shardings."""
        if self._cfg.mesh is None:
            # uncommitted numpy feeds the executable directly (default device)
            return payload, mask
        batch_sh = self._batch_sharding()
        rep_sh = self._replicated_sharding()
        n_rows = mask.shape[0]
        payload = jax.tree.map(
            lambda x: jax.device_put(x, batch_sh if is_batch_leaf(x, n_rows) else rep_sh)
            if isinstance(x, (np.ndarray, jnp.ndarray))
            else x,
            payload,
        )
        return payload, jax.device_put(mask, batch_sh)

    def _bound_inflight(self, token: Any) -> Optional[float]:
        """Enforce the double-buffering depth via step tokens; returns the
        observed sync µs when the dispatcher had to block."""
        self._inflight.append(token)
        if self._serialize:
            t0 = time.perf_counter()
            jax.block_until_ready(token)
            self._inflight.clear()
            return (time.perf_counter() - t0) * 1e6
        if len(self._inflight) <= max(1, self._cfg.in_flight):
            return None
        oldest = self._inflight.popleft()
        t0 = time.perf_counter()
        jax.block_until_ready(oldest)
        return (time.perf_counter() - t0) * 1e6
