"""Streaming evaluation engine — metrics as a service (SURVEY.md north star).

The reference (TorchMetrics) is a passive library: every ``update()`` is a
synchronous eager dispatch and every new input shape is a fresh trace. Serving
sustained traffic on TPU needs the opposite contract (arXiv:2605.25645,
arXiv:2204.06514): a CLOSED set of ahead-of-time compiled programs plus
host-side batching and queueing. This package supplies it:

* :mod:`~metrics_tpu.engine.bucketing` — shape bucketing + padding policy:
  incoming ragged batches round up to a small configurable set of padded batch
  sizes, with a validity mask so pad rows are inert
  (``Metric.update_state_masked``). The executable set is closed by
  construction.
* :mod:`~metrics_tpu.engine.aot` — AOT compilation cache: the per-bucket
  ``update`` / ``compute`` programs are lowered and compiled ONCE per
  (bucket signature, mesh, dtype), with hit/miss counters, optionally backed
  by JAX's persistent compilation cache directory so a warm process restart
  pays zero XLA compiles.
* :mod:`~metrics_tpu.engine.arena` — state arenas: the carried state packs to
  ONE contiguous donated buffer per dtype (static slice metadata, unpacked
  inside the jitted step where XLA fuses it away), so a step dispatch carries
  2–3 arrays instead of one per state leaf — the dispatch-amortization that
  matters at small batch sizes.
* :mod:`~metrics_tpu.engine.pipeline` — the :class:`StreamingEngine`: a
  bounded host ingestion queue (blocking ``submit`` = backpressure), megabatch
  coalescing (up to ``coalesce`` compatible queued batches concatenate into
  one masked step), an async dispatcher thread that pads/uploads the next
  batch while the device runs the current step (double buffering via JAX
  async dispatch, bounded by ``in_flight``), donated state buffers, and
  mesh-aware sharded steps in two sync modes — ``mesh_sync="step"``
  (per-step psum-merged deltas, globally consistent carried state) and
  ``mesh_sync="deferred"`` (shard-local states, COLLECTIVE-FREE steady
  steps, one fused merge bundle at ``result()``/snapshot boundaries — the
  reference's per-process accumulation semantics, and the mode that serves
  ``cat``/scan metrics like ``AUROC(capacity=N)`` on a mesh). Gates:
  ``make mesh-smoke`` (:mod:`~metrics_tpu.engine.mesh_smoke`), bench entry
  ``engine_mesh_dispatch`` (:mod:`~metrics_tpu.engine.mesh_bench`).
* :mod:`~metrics_tpu.engine.multistream` — :class:`MultiStreamEngine`: S
  independent evaluation streams served by ONE executable (stream-stacked
  states, per-row stream ids scatter-reduced via segment ops, per-stream
  compute with a runtime stream index).
* :mod:`~metrics_tpu.engine.snapshot` / :mod:`~metrics_tpu.engine.stats` —
  periodic atomic snapshots of the accumulated state (orbax-backed, resumable
  after a kill) and ring-buffer telemetry (queue depth, padding waste,
  compile-cache hits, step latency spread) exported as JSON.
* :mod:`~metrics_tpu.engine.fleet` — multi-host SPMD serving (ISSUE 15):
  :class:`FleetEngine` runs one per-host ingestion pipeline per
  ``jax.distributed`` process under a collective-free steady state, folds
  results over a one-device-per-host fleet mesh at explicit boundaries, and
  writes globally consistent snapshot cuts through a deterministic
  barrier-on-batch-boundary protocol. Gate: ``make fleet-smoke`` (two real
  CPU processes over gloo, :mod:`~metrics_tpu.engine.fleet.harness`).
* :mod:`~metrics_tpu.engine.model_host` — embedded-model serving (ISSUE 19):
  :class:`ModelHost` keeps ONE resident copy of an embedded model (Inception's
  tensor-sharded stem, a pipeline-staged encoder with ``ppermute`` handoff)
  and serves feature requests from many metric streams through bucketing,
  megabatch coalescing, and per-(bucket, precision, mesh) AOT executables —
  zero steady-state compiles, f32 bit-exact by default with bf16/int8
  activation paths under the q8 analytic bound. ``FID``/``KID``/``BERTScore``
  route through it via ``model_host=``. Gate: ``make model-smoke``
  (:mod:`~metrics_tpu.engine.model_smoke`).
* :mod:`~metrics_tpu.engine.quantize` — the block-scaled int8 codec for
  state at REST (ISSUE 10): ``EngineConfig(compress_payloads=True)`` stores
  snapshot payloads and pager spill rows quantized under the metric's
  ``sync_precision`` policy — the same policy that rides the wire through
  ``parallel/collectives.py``'s quantized collective rider. Gate:
  ``make quant-smoke`` (:mod:`~metrics_tpu.engine.quant_smoke`).

Quickstart::

    from metrics_tpu import Accuracy
    from metrics_tpu.engine import EngineConfig, StreamingEngine

    engine = StreamingEngine(Accuracy(), EngineConfig(buckets=(128, 512)))
    with engine:
        for preds, target in stream:      # ragged batch sizes welcome
            engine.submit(preds, target)  # blocks when the queue is full
        value = engine.result()           # flush + compiled compute

See ``docs/serving.md`` for the architecture and recovery semantics.
"""
from metrics_tpu.engine.admission import (
    AdmissionPolicy,
    AdmissionRejected,
    DegradationLadder,
    OverloadDetector,
    TokenBucket,
)
from metrics_tpu.engine.aot import AotCache, enable_persistent_compilation_cache
from metrics_tpu.engine.arena import ArenaLayout
from metrics_tpu.engine.bucketing import BucketPolicy
from metrics_tpu.engine.faults import (
    BackpressureTimeout,
    BoundaryMergeError,
    EngineDispatchError,
    FaultInjector,
    FaultSpec,
    InjectedFault,
    QuarantineRecord,
    ScreenPolicy,
    SnapshotCorruptError,
    StepTimeoutError,
)
from metrics_tpu.engine.fleet import (
    FleetBarrierError,
    FleetConfig,
    FleetEngine,
    FleetHostLostError,
    FleetTopologyError,
    restore_fleet_into,
)
from metrics_tpu.engine.model_host import (
    ModelHost,
    ModelHostConfig,
    encoder_host,
    inception_host,
    reset_host_registry,
    shared_host,
)
from metrics_tpu.engine.multistream import MultiStreamEngine
from metrics_tpu.engine.pipeline import EngineConfig, StreamingEngine
from metrics_tpu.engine.ragged import GroupedStateMetric, RaggedEngine
from metrics_tpu.engine.quantize import (
    ArenaRowCodec,
    decode_state_tree,
    encode_state_tree,
    q8_decode_array,
    q8_encode_array,
)
from metrics_tpu.engine.snapshot import (
    generations,
    latest_snapshot,
    load_snapshot,
    save_snapshot,
)
from metrics_tpu.engine.stats import EngineStats
from metrics_tpu.engine.trace import (
    DEFAULT_LATENCY_BUCKETS_US,
    FixedBucketHistogram,
    TraceRecorder,
    device_trace_session,
    render_openmetrics,
)
from metrics_tpu.engine.tracker import DriftAlarm, DriftAlarmError, DriftDetector
from metrics_tpu.engine.windows import WindowPolicy

__all__ = [
    "AdmissionPolicy",
    "AdmissionRejected",
    "AotCache",
    "ArenaLayout",
    "ArenaRowCodec",
    "BackpressureTimeout",
    "BoundaryMergeError",
    "BucketPolicy",
    "DEFAULT_LATENCY_BUCKETS_US",
    "DegradationLadder",
    "DriftAlarm",
    "DriftAlarmError",
    "DriftDetector",
    "EngineConfig",
    "EngineDispatchError",
    "EngineStats",
    "FaultInjector",
    "FaultSpec",
    "FixedBucketHistogram",
    "FleetBarrierError",
    "FleetConfig",
    "FleetEngine",
    "FleetHostLostError",
    "FleetTopologyError",
    "GroupedStateMetric",
    "InjectedFault",
    "ModelHost",
    "ModelHostConfig",
    "MultiStreamEngine",
    "OverloadDetector",
    "QuarantineRecord",
    "RaggedEngine",
    "ScreenPolicy",
    "SnapshotCorruptError",
    "StepTimeoutError",
    "StreamingEngine",
    "TokenBucket",
    "TraceRecorder",
    "WindowPolicy",
    "decode_state_tree",
    "device_trace_session",
    "enable_persistent_compilation_cache",
    "encode_state_tree",
    "encoder_host",
    "generations",
    "inception_host",
    "latest_snapshot",
    "load_snapshot",
    "q8_decode_array",
    "q8_encode_array",
    "render_openmetrics",
    "reset_host_registry",
    "restore_fleet_into",
    "save_snapshot",
    "shared_host",
]
