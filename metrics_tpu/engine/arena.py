"""State arenas: pack a metric-state pytree into one buffer per dtype.

Why: the streaming engine's steady state is dispatch-bound at small batch
sizes — each AOT step call flattens the state pytree, type-checks every leaf,
and hands XLA one donated buffer PER LEAF. A `MetricCollection` of a handful
of classification metrics easily carries 10–20 small leaves, so the per-step
host overhead scales with metric count, not with work. The arena collapses
that: all state leaves of one dtype concatenate (raveled) into a single
contiguous 1-D buffer, so a step dispatch carries 2–3 donated arrays — one per
dtype class — no matter how many metrics the collection serves.

The packing plan (:class:`ArenaLayout`) is STATIC metadata derived from the
metric's ``abstract_state()``: per leaf, the owning dtype segment, its offset,
flat size, and logical shape. ``unpack`` re-slices with static offsets inside
the jitted step, which XLA fuses away — the compiled program reads the same
values it would have read from separate buffers; only the dispatch-time
argument count changes. ``pack`` of the updated tree is likewise a per-dtype
concatenate of raveled leaves that XLA writes straight into the donated input
buffer (shapes and dtypes match exactly, the donation fast path).

Invariants (guarded by ``tests/engine/test_arena.py``):

* one buffer per distinct state dtype — donated step arguments per dtype
  class == 1, and a typical classification collection packs to ≤ 3 buffers
  (float, int, bool);
* ``unpack(pack(tree)) == tree`` bit-exactly, traced or eager;
* buffer keys are dtype names, so the arena dict is a stable pytree (sorted
  keys) and snapshots serialize ONE payload per dtype
  (``engine/snapshot.py``).

Dtype segregation is what keeps this exact: mixing dtypes in one buffer would
force casts (lossy for int64→float32 counters) — per-dtype buffers are pure
relayouts.
"""
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ArenaLayout"]


class _LeafSpec:
    __slots__ = ("key", "offset", "size", "shape", "dtype")

    def __init__(self, key: str, offset: int, size: int, shape: Tuple[int, ...], dtype: Any):
        self.key = key
        self.offset = offset
        self.size = size
        self.shape = shape
        self.dtype = dtype


class ArenaLayout:
    """Static plan for packing a state pytree into per-dtype 1-D buffers.

    Build one from a metric via :meth:`Metric.arena_layout` (or directly with
    :meth:`for_state` on any ``ShapeDtypeStruct`` pytree). The layout is pure
    metadata — no device buffers — and is safe to share across engines over
    equivalently-shaped states.
    """

    def __init__(self, treedef: Any, specs: List[_LeafSpec], totals: Dict[str, int]):
        self._treedef = treedef
        self._specs = specs
        self._totals = totals  # dtype key -> flat element count

    @classmethod
    def for_state(cls, abstract_state: Any) -> "ArenaLayout":
        """Derive the packing plan from a ``ShapeDtypeStruct`` (or array)
        pytree. Every leaf must be array-shaped — list/cat states have no
        static arena slot (the engine refuses those metrics earlier)."""
        leaves, treedef = jax.tree_util.tree_flatten(abstract_state)
        totals: Dict[str, int] = {}
        specs: List[_LeafSpec] = []
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                raise ValueError(
                    f"arena layouts need array-shaped state leaves, got {type(leaf).__name__}"
                )
            key = jnp.dtype(dtype).name
            size = 1
            for d in shape:
                size *= int(d)
            specs.append(_LeafSpec(key, totals.get(key, 0), size, tuple(int(d) for d in shape), jnp.dtype(dtype)))
            totals[key] = totals.get(key, 0) + size
        return cls(treedef, specs, totals)

    # ------------------------------------------------------------------ queries

    @property
    def num_buffers(self) -> int:
        """Distinct dtype segments == donated step arguments for the state."""
        return len(self._totals)

    @property
    def dtype_keys(self) -> Tuple[str, ...]:
        return tuple(sorted(self._totals))

    @property
    def num_leaves(self) -> int:
        return len(self._specs)

    def buffer_sizes(self) -> Dict[str, int]:
        """Flat element count per dtype buffer."""
        return dict(self._totals)

    def leaf_slices(self) -> Tuple[Tuple[str, int, int, Tuple[int, ...], Any], ...]:
        """The full static packing plan, one ``(dtype_key, offset, size,
        shape, dtype)`` tuple per leaf in tree-flatten order. This is the
        slice metadata the whole-step megakernel walks
        (``engine/megastep.py``): column ``offset + i`` of dtype ``key``'s
        packed buffer is element ``i`` of that leaf's ravel."""
        return tuple((s.key, s.offset, s.size, s.shape, s.dtype) for s in self._specs)

    def column_ops(self, leaf_ops: Sequence[int]) -> Dict[str, np.ndarray]:
        """Expand a PER-LEAF integer opcode list (tree-flatten order, one
        entry per leaf — e.g. each leaf's reduction opcode) into per-dtype
        opcode COLUMN rows aligned with the packed buffers: ``out[key][c]`` is
        the opcode of whichever leaf owns column ``c``. Host metadata (numpy),
        never traced — the megastep kernels bake it in as a constant."""
        if len(leaf_ops) != len(self._specs):
            raise ValueError(
                f"got {len(leaf_ops)} leaf opcodes, layout has {len(self._specs)} leaves"
            )
        rows = {k: np.zeros((n,), np.int32) for k, n in self._totals.items()}
        for spec, op in zip(self._specs, leaf_ops):
            rows[spec.key][spec.offset : spec.offset + spec.size] = int(op)
        return rows

    def abstract(self) -> Dict[str, jax.ShapeDtypeStruct]:
        """``ShapeDtypeStruct`` arena dict — the AOT lowering template."""
        return {
            k: jax.ShapeDtypeStruct((n,), jnp.dtype(k)) for k, n in self._totals.items()
        }

    def abstract_stacked(self, world: int) -> Dict[str, jax.ShapeDtypeStruct]:
        """``ShapeDtypeStruct`` dict of the SHARD-STACKED arena: one
        ``(world, n)`` buffer per dtype, row ``k`` = shard ``k``'s local arena.
        The deferred-sync mesh engine's carried-state template — sharded over
        the mesh axis on dim 0, each device owns exactly its own row."""
        return {
            k: jax.ShapeDtypeStruct((int(world), n), jnp.dtype(k))
            for k, n in self._totals.items()
        }

    def abstract_paned(self, panes: int) -> Dict[str, jax.ShapeDtypeStruct]:
        """``ShapeDtypeStruct`` dict of the PANE-RING arena (ISSUE 13): one
        ``(panes, n)`` buffer per dtype, row ``p`` = pane ``p``'s packed state
        for this layout. The windowed engine's single-device carried form —
        the step updates one runtime-indexed row, rotation init-fills one row,
        and ``result()`` folds the live rows via ``merge_stacked_states``.
        Structurally identical to :meth:`abstract_stacked`; the separate name
        keeps the two leading-axis meanings (shard vs pane) distinct at call
        sites."""
        return self.abstract_stacked(panes)

    def abstract_stream_stacked(self, world: int, rows: int) -> Dict[str, jax.ShapeDtypeStruct]:
        """``ShapeDtypeStruct`` dict of the STREAM-SHARDED paged arena: one
        ``(world, rows, n)`` buffer per dtype, where this layout describes ONE
        stream's state (``n`` = one stream's flat element count per dtype) and
        ``rows`` is the per-shard resident-slot count. Row ``(k, j)`` is shard
        ``k``'s slot ``j`` — a contiguous per-dtype vector, which is what lets
        the pager spill/fault single streams without touching their
        neighbours. Dim 0 shards over the mesh axis; within a shard,
        :meth:`unpack_stacked`/:meth:`pack_stacked` convert ``(rows, n)``
        buffers to/from the slot-stacked logical state tree."""
        return {
            k: jax.ShapeDtypeStruct((int(world), int(rows), n), jnp.dtype(k))
            for k, n in self._totals.items()
        }

    def matches(
        self,
        arena: Dict[str, Any],
        world: Optional[int] = None,
        panes: Optional[int] = None,
    ) -> bool:
        """Shape/dtype compatibility of the BUFFERS (used when restoring
        snapshots); with ``world`` the expected form is the shard-stacked
        ``(world, n)`` layout, with ``panes`` the pane-ring ``(panes, n)``
        form, and with both the deferred windowed ``(world, panes, n)`` form.
        Necessary but not sufficient — two layouts with permuted same-dtype
        leaves have identical buffers; :meth:`fingerprint` is the sufficient
        check and travels in the snapshot meta."""
        if set(arena) != set(self._totals):
            return False
        lead: Tuple[int, ...] = ()
        if world is not None:
            lead += (int(world),)
        if panes is not None:
            lead += (int(panes),)
        return all(
            tuple(getattr(arena[k], "shape", ())) == lead + (n,)
            for k, n in self._totals.items()
        )

    def fingerprint(self) -> str:
        """Digest of the full packing plan — treedef + every leaf's (segment,
        offset, size, shape, dtype). Two layouts unpack a buffer identically
        iff their fingerprints match; the engine stores this in snapshot meta
        so a reconfigured metric cannot silently unscramble a stale arena."""
        import hashlib

        h = hashlib.sha256(repr(self._treedef).encode())
        for s in self._specs:
            h.update(f"{s.key}:{s.offset}:{s.size}:{s.shape}:{s.dtype}".encode())
        return h.hexdigest()[:16]

    @staticmethod
    def clone_buffers(arena: Dict[str, Any]) -> Dict[str, Any]:
        """Device copy of a packed arena — the engine's donation-aware SHADOW
        for transactional steps: when the live buffers are about to be
        DONATED into a step, this retained copy is what a failed step rolls
        back onto. One copy per dtype buffer (2–3 arrays), not per leaf —
        the same amortization the arena gives dispatch applies to the shadow.
        Shardings are preserved (``jnp.array(copy=True)`` copies per-shard)."""
        return {k: jnp.array(v, copy=True) for k, v in arena.items()}

    # ------------------------------------------------------------- pack / unpack

    def pack(self, state: Any) -> Dict[str, Any]:
        """State pytree -> per-dtype 1-D buffers. Traced or eager; inside the
        jitted step the concatenate writes straight into the donated input."""
        leaves = jax.tree_util.tree_flatten(state)[0]
        if len(leaves) != len(self._specs):
            raise ValueError(
                f"state has {len(leaves)} leaves, layout expects {len(self._specs)}"
            )
        parts: Dict[str, List[Any]] = {k: [] for k in self._totals}
        for leaf, spec in zip(leaves, self._specs):
            parts[spec.key].append(jnp.ravel(jnp.asarray(leaf, spec.dtype)))
        return {
            k: (jnp.concatenate(chunks) if len(chunks) > 1 else chunks[0])
            for k, chunks in parts.items()
        }

    def unpack(self, arena: Dict[str, Any]) -> Any:
        """Per-dtype buffers -> state pytree via STATIC slices (XLA fuses these
        into the consuming ops; no copies survive in the compiled step)."""
        leaves = [
            jnp.reshape(arena[s.key][s.offset : s.offset + s.size], s.shape)
            for s in self._specs
        ]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    # ------------------------------------------------------ shard-stacked views

    def pack_stacked(self, state: Any, lead: int = 1) -> Dict[str, Any]:
        """Stacked state pytree (``lead`` leading stack axes) -> per-dtype
        ``leading + (n,)`` buffers: the per-row packing applied row-wise.
        ``lead=1`` is the deferred-sync engine's shard-stacked ``(world, n)``
        carried form (dim 0 shards over the mesh axis, so inside the step each
        device packs/unpacks only its own row) and the windowed engine's
        pane-ring ``(panes, n)`` form; ``lead=2`` is the deferred WINDOWED
        ``(world, panes, n)`` form (ISSUE 13)."""
        leaves = jax.tree_util.tree_flatten(state)[0]
        if len(leaves) != len(self._specs):
            raise ValueError(
                f"state has {len(leaves)} leaves, layout expects {len(self._specs)}"
            )
        parts: Dict[str, List[Any]] = {k: [] for k in self._totals}
        for leaf, spec in zip(leaves, self._specs):
            arr = jnp.asarray(leaf, spec.dtype)
            parts[spec.key].append(
                jnp.reshape(arr, tuple(arr.shape[:lead]) + (spec.size,))
            )
        return {
            k: (jnp.concatenate(chunks, axis=lead) if len(chunks) > 1 else chunks[0])
            for k, chunks in parts.items()
        }

    def unpack_stacked(self, arena: Dict[str, Any], lead: int = 1) -> Any:
        """Inverse of :meth:`pack_stacked`: ``leading + (n,)`` buffers -> the
        stacked state pytree (every leaf gains the ``lead`` leading axes).
        With ``lead=1`` this is the MERGED-VIEW precursor: feeding the result
        to ``Metric.merge_stacked_states`` yields the global state the
        reference's ``dist_reduce_fx`` sync would produce."""
        first = next(iter(arena.values()))
        leading = tuple(int(d) for d in jnp.shape(first)[:lead])
        leaves = [
            jnp.reshape(
                arena[s.key][..., s.offset : s.offset + s.size], leading + s.shape
            )
            for s in self._specs
        ]
        return jax.tree_util.tree_unflatten(self._treedef, leaves)

    def __repr__(self) -> str:
        segs = ", ".join(f"{k}:{n}" for k, n in sorted(self._totals.items()))
        return f"ArenaLayout({len(self._specs)} leaves -> {self.num_buffers} buffers [{segs}])"
