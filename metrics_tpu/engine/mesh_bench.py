"""Mesh steady-state dispatch bench: ``python -m metrics_tpu.engine.mesh_bench``.

The ``engine_mesh_dispatch`` entry (bench.py / MULTICHIP): step-sync vs
deferred-sync steady-state rate on the 8-device mesh, measured in ONE run —
one process, one mesh, one fixed-seed data stream — so the RATIO between the
modes is the durable fact even when the absolute rates are host-noise-bound
(virtual CPU meshes timeshare one host → ``liveness_only``).

PINNED protocol (docs/benchmarking.md, "Mesh steady state (r8)"):
fixed-seed 192-batch stream of uniform 64..256-row batches against buckets
(256,) and a small-state ``MetricCollection([Accuracy(), MeanSquaredError()])``;
``coalesce=1`` so steps == padded chunks in both modes and steps/s compares
like for like; ``in_flight=1`` so BOTH modes run the same synchronous step
discipline — a CPU step-sync mesh serializes every step regardless (the
communicator-deadlock policy), and letting only the deferred mode pipeline
would conflate the collective win with overlap (and on a small host, with
thread contention): with both modes blocking per step, the ratio isolates
exactly what deferred sync deletes — the per-step cross-shard merge. Per
mode one warmup stream pays every compile (update + compute, + the boundary
merge for deferred), then 5 INTERLEAVED (step, deferred) timed stream pairs
via ``reset()``, each ended by flush + a host fetch of the computed value
(value-fetched timing — the deferred mode's boundary merge is INSIDE the
timed region, so its collective cost is charged, not hidden); the headline
speedup is the aggregate step/deferred time ratio over the pairs, and
``steady_step_latency`` isolates the two step EXECUTABLES' back-to-back
latency (the engine rates add a mode-independent host term that dilutes the
ratio toward 1 on a host-noise-bound virtual mesh); ZERO steady-state
compiles asserted per mode. Prints one JSON document on stdout.
"""
import json
import os
import sys
import time

NUM_DEVICES = 8


def run_bench() -> dict:
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import EngineConfig, StreamingEngine

    devs = jax.devices()
    if len(devs) < NUM_DEVICES:
        return {"error": f"need {NUM_DEVICES} devices, have {len(devs)}"}
    mesh = Mesh(np.asarray(devs[:NUM_DEVICES]), ("dp",))
    platform = devs[0].platform

    buckets = (256,)
    n_batches, trials = 192, 5
    rng = np.random.RandomState(20260803)
    sizes = rng.randint(64, 257, size=n_batches)
    batches = [
        (rng.rand(n).astype(np.float32), (rng.rand(n) > 0.5).astype(np.int32))
        for n in sizes
    ]
    rows_total = int(sum(sizes))

    def col():
        return MetricCollection([Accuracy(), MeanSquaredError()])

    def make_engine(mode: str) -> StreamingEngine:
        return StreamingEngine(
            col(),
            EngineConfig(
                buckets=buckets, mesh=mesh, axis="dp", mesh_sync=mode,
                coalesce=1, in_flight=1, max_queue=n_batches + 1,
                telemetry_capacity=512,
            ),
        )

    def stream_once(engine: StreamingEngine) -> float:
        t0 = time.perf_counter()
        for b in batches:
            engine.submit(*b)
        engine.flush()
        res = engine.result()  # value-fetched: merge + compute inside the timing
        float(next(iter(res.values())))
        return time.perf_counter() - t0

    # both engines live in one process and the trial streams INTERLEAVE
    # (step, deferred, step, deferred, ...): host-load drift — the dominant
    # noise on a timeshared virtual mesh — hits both modes of a pair alike
    # and cancels in the per-pair ratio
    engines = {m: make_engine(m) for m in ("step", "deferred")}
    times = {m: [] for m in engines}
    steps_per_stream = {}
    warm_misses = {}
    steady = {}
    for m, e in engines.items():
        e.start()
        stream_once(e)  # warmup: every compile (incl. deferred merge) lands here
        steps_per_stream[m] = e.steps  # reset() rewinds the counter below
        warm_misses[m] = e.aot_cache.misses
    for _ in range(trials):
        for m, e in engines.items():
            e.reset()
            times[m].append(stream_once(e))
    for m, e in engines.items():
        steady[m] = e.aot_cache.misses - warm_misses[m]
        if steady[m]:
            raise RuntimeError(
                f"engine_mesh_dispatch[{m}] steady state compiled "
                f"{steady[m]} programs; the closed-program contract is broken"
            )

    def summarize(m: str) -> dict:
        e = engines[m]
        tele = e.telemetry()
        ts = sorted(times[m])
        med = ts[len(ts) // 2]
        shares = tele.get("host_time_shares", {})
        sync_info = tele.get("mesh_sync", {})
        e.stop()
        return {
            "samples_per_s": round(rows_total / med, 1),
            "steps_per_s": round(steps_per_stream[m] / med, 1),
            "steps_per_stream": steps_per_stream[m],
            "spread_frac": round((ts[-1] - ts[0]) / med, 3),
            "compiles_steady_state": steady[m],
            "regime": shares.get("regime"),
            "collective_share": sync_info.get("collective_share"),
            "boundary_merges": sync_info.get("merges"),
        }

    def step_latency() -> dict:
        """Back-to-back latency of the two STEADY-STEP executables themselves
        (pre-padded, pre-sharded inputs, carried state, blocking on the token
        per call — the engine's synchronous step discipline minus its host
        pad/queue/bookkeeping). This isolates exactly what deferred sync
        deletes from the hot path: the in-step collective. Interleaved
        K-call reps; median of per-rep ratios."""
        reps, k = 5, 40
        bucket = buckets[-1]
        p = rng.rand(bucket).astype(np.float32)
        t = (rng.rand(bucket) > 0.5).astype(np.int32)
        mask = np.ones(bucket, bool)
        progs, states, uploads = {}, {}, {}
        for m, e in engines.items():
            progs[m] = e._update_program(((p, t), {}), mask)
            states[m] = e._put_state(e._init_state_tree())
            uploads[m] = e._upload(((p, t), {}), mask)
        lat = {m: [] for m in engines}
        for _ in range(reps):
            for m in engines:
                payload, mask_dev = uploads[m]
                t0 = time.perf_counter()
                for _ in range(k):
                    states[m], token = progs[m](states[m], payload, mask_dev)
                    jax.block_until_ready(token)
                lat[m].append((time.perf_counter() - t0) / k * 1e3)
        rep_ratios = sorted(s / d for s, d in zip(lat["step"], lat["deferred"]))
        return {
            "step_ms": round(sorted(lat["step"])[reps // 2], 3),
            "deferred_ms": round(sorted(lat["deferred"])[reps // 2], 3),
            "ratio_step_over_deferred": round(rep_ratios[reps // 2], 3),
            "rep_ratios": [round(r, 3) for r in rep_ratios],
            "protocol": f"{reps} interleaved reps x {k} blocking calls, bucket {bucket}",
        }

    latency = step_latency()
    out = {m: summarize(m) for m in engines}
    pair_ratios = sorted(s / d for s, d in zip(times["step"], times["deferred"]))
    # headline = AGGREGATE time ratio over the interleaved trials: per-stream
    # step-sync times are bimodal on a timeshared host (the 8-thread
    # rendezvous is scheduler roulette), so a single pair can swing either
    # way; the sum spans every scheduling regime both modes saw
    ratio = sum(times["step"]) / sum(times["deferred"])
    doc = {
        **out,
        # the acceptance ratio: collective-free steady steps vs per-step
        # psum-merge — aggregate time ratio over the interleaved trials
        # (per-pair ratios reported alongside for spread)
        "speedup_deferred_vs_step": round(ratio, 3),
        "pair_ratios": [round(r, 3) for r in pair_ratios],
        # the per-step executable latencies: the collective-cost isolate (the
        # engine rates above add the mode-independent host pad/queue/dispatch
        # term, which dilutes the ratio toward 1 on a host-noise-bound mesh)
        "steady_step_latency": latency,
        "rows_per_stream": rows_total,
        "batches_per_stream": n_batches,
        "batch_rows_range": [64, 256],
        "buckets": list(buckets),
        "trials": trials,
        "n_devices": NUM_DEVICES,
        "platform": platform,
        "protocol": (
            "fixed-seed 192-batch stream, 64..256 rows/batch, buckets (256,), "
            "coalesce=1, in_flight=1 (both modes step synchronously: the ratio "
            "isolates the per-step collective, not pipelining), small-state "
            "collection; both engines in ONE process, 1 warmup stream each pays all "
            "compiles, then 5 INTERLEAVED (step, deferred) timed stream pairs via "
            "reset(), value-fetched (deferred boundary merge inside the timing); "
            "speedup = aggregate step/deferred time ratio over the interleaved "
            "trials (per-pair ratios reported for spread), rates = per-mode medians "
            "with (max-min)/median spread; steady_step_latency = interleaved K-call "
            "executable latency pair; zero steady-state compiles asserted per mode"
        ),
    }
    if platform == "cpu":
        doc["liveness_only"] = True
        doc["note"] = (
            "virtual CPU mesh timeshares one host: rates are liveness, the durable "
            "facts are the step-vs-deferred RATIO (shared run) + zero steady compiles "
            "+ the collective placement pinned by mesh-smoke/tests"
        )
    return doc


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    print(json.dumps(run_bench()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
