"""Windowed-semantics smoke: ``python -m metrics_tpu.engine.windows_smoke``.

The CPU-safe CI gate for the pane-ring window layer (ISSUE 13,
``make windows-smoke``), on an 8-device virtual mesh it bootstraps itself
(``--xla_force_host_platform_device_count``, the mesh-smoke recipe):

1. **Tumbling oracle** — a deferred-sync mesh engine under
   ``tumbling(pane_batches=k)``: at EVERY pane boundary the engine's
   ``result()`` is bit-identical to a FRESH single-device engine fed only
   that pane's batches (the fresh-engine-per-pane oracle — the acceptance
   criterion's exactness claim).
2. **Sliding fold** — ``sliding(n_panes=P)`` on the same mesh equals a fresh
   engine fed the last P panes' batches, at every boundary (the
   ``merge_stacked_states`` pane fold vs recompute-from-scratch).
3. **Zero steady compiles** — after the ring has rotated once (every window
   program compiled), ``>= 3`` further rotations produce an AOT cache
   miss-counter delta of EXACTLY zero: rotation is a slot-index bump plus a
   cached init-fill, never a retrace.
4. **Window x stream-shard with a pane spill** — S Zipfian streams sharded
   over the mesh behind a resident cap small enough that pane rows MUST
   spill to host RAM (``page_outs >= 1``): every stream's sliding result
   matches its fresh-engine oracle bit-exactly through the spill.
5. **Kill/resume mid-ring** — a snapshot cadence that lands MID-pane: the
   resumed engine (pane cursor + rotation marks restored from provenance)
   replays the stream tail to a bit-identical windowed result.
6. **Drift determinism** — seeded label-drift traffic through a tumbling
   engine with a wired :class:`DriftDetector` raises at least one alarm,
   and two same-seed runs produce IDENTICAL pane histories and alarm lists.

Prints one PASS line; exits nonzero on any violated claim.
"""
import os
import subprocess
import sys

NUM_DEVICES = 8


def _bootstrap() -> int:
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + f" --xla_force_host_platform_device_count={NUM_DEVICES}"
    )
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        "import sys; from metrics_tpu.engine.windows_smoke import _impl; sys.exit(_impl())"
    )
    proc = subprocess.run([sys.executable, "-c", code], env=env, timeout=900)
    return proc.returncode


def _impl() -> int:
    import tempfile

    import numpy as np

    import jax
    from jax.sharding import Mesh

    from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
    from metrics_tpu.engine import (
        DriftDetector,
        EngineConfig,
        MultiStreamEngine,
        StreamingEngine,
        WindowPolicy,
    )
    from metrics_tpu.engine.chaos_smoke import make_checker
    from metrics_tpu.engine.traffic import zipf_traffic

    devs = jax.devices()
    if len(devs) < NUM_DEVICES:
        print(f"FAIL: need {NUM_DEVICES} devices, have {len(devs)}")
        return 1
    mesh = Mesh(np.asarray(devs[:NUM_DEVICES]), ("dp",))
    _check, _failed = make_checker()

    def col():
        return MetricCollection([Accuracy(), MeanSquaredError()])

    rng = np.random.RandomState(0)
    batches = [
        (
            (rng.randint(0, 65, size=n) / 64.0).astype(np.float32),
            (rng.rand(n) > 0.5).astype(np.int32),
        )
        for n in (13, 32, 7, 29, 18, 9, 24, 11, 5, 21, 16, 3)
    ]
    PANE = 3  # batches per pane

    def oracle(bs):
        e = StreamingEngine(col(), EngineConfig(buckets=(32,)))
        with e:
            for b in bs:
                e.submit(*b)
            return {k: np.asarray(v) for k, v in e.result().items()}

    # ---------------------------------------- 1. tumbling vs per-pane oracle
    # rotation happens at the boundary batch's own group, so a read right
    # after batch i (one short of the boundary) sees the OPEN pane: exactly
    # the batches since the last rotation — a fresh engine fed only those
    # must match bit for bit, at every pane of the stream
    tum3 = StreamingEngine(
        col(),
        EngineConfig(
            buckets=(32,), coalesce=1, mesh=mesh, axis="dp", mesh_sync="deferred",
            window=WindowPolicy.tumbling(pane_batches=PANE, n_panes=2),
        ),
    )
    with tum3:
        boundaries = 0
        for i, b in enumerate(batches):
            tum3.submit(*b)
            if (i + 1) % PANE == PANE - 1 and i >= PANE:
                # mid-pane read: the open pane holds batches since the last
                # boundary — bit-exact vs a fresh engine fed exactly those
                start = ((i + 1) // PANE) * PANE
                got = {k: np.asarray(v) for k, v in tum3.result().items()}
                want = oracle(batches[start : i + 1])
                for k in want:
                    _check(
                        np.array_equal(got[k], want[k]),
                        f"tumbling pane oracle diverged at batch {i}: "
                        f"{k} {got[k]} != {want[k]}",
                    )
                boundaries += 1
    _check(boundaries >= 3, f"tumbling oracle checked only {boundaries} panes")
    _check(tum3.rotations >= 3, f"tumbling rotated only {tum3.rotations}x")

    # ------------------------------------------- 2. sliding fold vs recompute
    P_SLIDE = 3
    sld = StreamingEngine(
        col(),
        EngineConfig(
            buckets=(32,), coalesce=1, mesh=mesh, axis="dp", mesh_sync="deferred",
            window=WindowPolicy.sliding(n_panes=P_SLIDE, pane_batches=PANE),
        ),
    )
    with sld:
        for i, b in enumerate(batches):
            sld.submit(*b)
            if (i + 1) % PANE == PANE - 1 and i >= PANE:
                cur_start = ((i + 1) // PANE) * PANE
                win_start = max(0, cur_start - (P_SLIDE - 1) * PANE)
                got = {k: np.asarray(v) for k, v in sld.result().items()}
                want = oracle(batches[win_start : i + 1])
                for k in want:
                    _check(
                        np.array_equal(got[k], want[k]),
                        f"sliding fold diverged at batch {i}: {k} {got[k]} != {want[k]}",
                    )

    # ------------------------------- 3. zero steady compiles across rotations
    zc = StreamingEngine(
        col(),
        EngineConfig(
            buckets=(32,), coalesce=1, mesh=mesh, axis="dp", mesh_sync="deferred",
            window=WindowPolicy.sliding(n_panes=P_SLIDE, pane_batches=PANE),
        ),
    )
    with zc:
        for b in batches[: PANE + 1]:
            zc.submit(*b)
        zc.result()  # ring rotated once; every window program compiled
        warm = zc.aot_cache.misses
        rot0 = zc.rotations
        for b in batches[PANE + 1 :]:
            zc.submit(*b)
        zc.result()
        steady = zc.aot_cache.misses - warm
    _check(zc.rotations - rot0 >= 3, f"only {zc.rotations - rot0} steady rotations")
    _check(
        steady == 0,
        f"{steady} compiles across {zc.rotations - rot0} rotations (expected 0 — "
        "rotation must be a slot-index bump, never a retrace)",
    )

    # --------------------- 4. window x stream-shard with a pane spill (Zipf)
    S = 12
    traffic = zipf_traffic(S, 48, alpha=1.1, seed=23, max_rows=8)
    ss = MultiStreamEngine(
        Accuracy(), S,
        EngineConfig(
            buckets=(32,), coalesce=1, mesh=mesh, axis="dp", mesh_sync="deferred",
            window=WindowPolicy.sliding(n_panes=2, pane_batches=12),
        ),
        stream_shard=True, resident_streams=2,
    )
    with ss:
        for sid, p, t in traffic:
            ss.submit(sid, p, t)
        got_ss = {sid: np.asarray(v) for sid, v in ss.results().items()}
    _check(ss.stats.page_outs >= 1, "resident cap never bound — no pane spill")
    # rotations land at 12/24/36/48: the final one opened a fresh pane, so
    # the live window is that empty pane + the [36:48) pane
    window_traffic = traffic[36:48]
    for sid in sorted({b[0] for b in window_traffic}):
        e = StreamingEngine(Accuracy(), EngineConfig(buckets=(32,)))
        with e:
            for bsid, p, t in window_traffic:
                if bsid == sid:
                    e.submit(p, t)
            want_v = np.asarray(e.result())
        _check(
            np.array_equal(got_ss[sid], want_v),
            f"stream-shard windowed parity: stream {sid} {got_ss[sid]} != {want_v}",
        )

    # --------------------------------------- 5. kill/resume mid-ring (exact)
    snapdir = tempfile.mkdtemp(prefix="metrics_tpu_windows_")
    w_cfg = dict(
        buckets=(32,), coalesce=1, mesh=mesh, axis="dp", mesh_sync="deferred",
        window=WindowPolicy.sliding(n_panes=P_SLIDE, pane_batches=PANE),
    )
    # snapshot_every=5 vs pane_batches=3 over 12 batches: the newest
    # generation lands at cursor 10 — one batch INTO a pane (mid-ring)
    ke = StreamingEngine(
        col(), EngineConfig(snapshot_every=5, snapshot_dir=snapdir, **w_cfg)
    )
    with ke:
        for b in batches:
            ke.submit(*b)
        want_k = {k: np.asarray(v) for k, v in ke.result().items()}
    del ke
    re = StreamingEngine(col(), EngineConfig(snapshot_dir=snapdir, **w_cfg))
    meta = re.restore()
    _check(
        int(meta["batches_done"]) % PANE != 0,
        f"snapshot landed on a pane boundary (cursor {meta['batches_done']}) — "
        "the mid-ring claim needs a mid-pane cursor",
    )
    with re:
        for b in batches[int(meta["batches_done"]) :]:
            re.submit(*b)
        got_k = {k: np.asarray(v) for k, v in re.result().items()}
    for k in want_k:
        _check(
            np.array_equal(got_k[k], want_k[k]),
            f"mid-ring kill/resume diverged: {k} {got_k[k]} != {want_k[k]}",
        )

    # ----------------------------------------- 6. drift alarm + determinism
    def drift_run():
        det = DriftDetector(threshold=0.2, up_after=2, down_after=2, baseline="first")
        # correlated labels (~0.92 agreement) make the flip drift a REAL
        # accuracy signal: pane accuracy walks from ~0.9 to ~0.5 and stays
        d_traffic = zipf_traffic(
            4, 72, seed=7, max_rows=8, label_acc=0.92,
            drift_at=36, drift_ramp=6, drift_flip=0.8,
        )
        eng = StreamingEngine(
            Accuracy(),
            EngineConfig(
                buckets=(32,), coalesce=1,
                window=WindowPolicy.tumbling(pane_batches=6),
                drift=det,
            ),
        )
        with eng:
            for _sid, p, t in d_traffic:
                eng.submit(p, t)
            eng.flush()
        return det, eng

    det_a, eng_a = drift_run()
    det_b, _eng_b = drift_run()
    _check(
        len(det_a.alarms("raise")) >= 1,
        f"label drift raised no alarm (history {det_a.history()})",
    )
    _check(
        det_a.history() == det_b.history()
        and [a.describe() for a in det_a.alarms()]
        == [a.describe() for a in det_b.alarms()],
        "same-seed drift runs diverged (history or alarm list)",
    )
    _check(
        eng_a.stats.drift_alarms >= 1 and eng_a.stats.drift_evals == eng_a.rotations,
        f"drift accounting wrong: {eng_a.stats.windows_summary()}",
    )

    if _failed:
        return 1
    print(
        "windows-smoke PASS: "
        f"tumbling bit-exact vs fresh-engine-per-pane oracle ({boundaries} panes, "
        f"8-dev deferred mesh); sliding fold exact vs recompute; "
        f"{zc.rotations - rot0} rotations with ZERO compiles; window x "
        f"stream-shard parity through {ss.stats.page_outs} pane spills "
        f"(S={S} Zipf, resident=2); mid-ring kill/resume exact from cursor "
        f"{meta['batches_done']}; drift alarm raised deterministically "
        f"({len(det_a.alarms('raise'))} raise / {len(det_a.alarms('clear'))} clear)"
    )
    return 0


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    if len(jax.devices()) < NUM_DEVICES:
        return _bootstrap()
    return _impl()


if __name__ == "__main__":
    sys.exit(main())
