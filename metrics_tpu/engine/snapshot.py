"""Crash-safe snapshots of accumulated engine state (orbax-backed, atomic).

Recovery contract: a snapshot directory always contains at least one COMPLETE
snapshot once any save finished, no matter when the process dies. This is the
reference's missing piece — its ``state_dict`` checkpointing
(``torchmetrics/metric.py:514``) rides the training framework's checkpoint
cadence; a serving engine owns its own.

Layout (one directory per engine)::

    <dir>/snap_000000000042_<ns>/   # orbax PyTreeCheckpointer dir (or .pkl);
    <dir>/snap_000000000084_<ns>/   # <ns> = creation time in ns, so a reset/
    <dir>/LATEST                    # restarted engine replaying the same step
                                    # numbers never rewrites an existing dir

Atomicity: the snapshot payload is written first, then ``LATEST`` is replaced
via write-to-temp + ``os.replace`` (atomic on POSIX). A kill mid-payload-write
leaves a garbage ``snap_*`` that ``LATEST`` never points to; a kill mid-pointer
leaves the previous pointer. ``load_snapshot`` follows ``LATEST`` by default.
Older snapshots beyond ``keep`` are garbage-collected after the pointer moves.

Integrity (ISSUE 6): every snapshot carries a checksum sidecar
(``integrity_<snap>.json`` — sha256 over a canonical serialization of the
whole payload: state leaves, meta, host attrs), written after the payload and
before the pointer moves. ``load_snapshot`` re-derives the digest from the
deserialized payload and raises a typed :class:`SnapshotCorruptError`
(naming the path and generation) on mismatch — the same typed error wraps
raw deserialization failures from truncated/bit-flipped payloads. The
``keep`` newest snapshots form a RETAINED GENERATION RING:
``load_snapshot(..., fallback=True)`` walks it newest-first past corrupt
generations, so a rotted ``LATEST`` payload degrades to the previous
generation (plus replay from its older cursor) instead of an outage —
``StreamingEngine.restore`` uses exactly this path and counts the fallback.

The payload rides the same orbax machinery as ``utils/checkpoint.py`` (numpy-
ified state pytree; pickle fallback when orbax is absent), plus a ``meta``
subtree carrying the step counter and row counts the engine needs to resume.
With state arenas (``engine/arena.py``) the state subtree is the arena dict
itself — ONE payload array per dtype, however many metrics the engine serves.

``host_attrs`` rides alongside: compute-relevant attributes a metric derives
from DATA during update (``Metric.host_compute_attrs`` — e.g. ``Accuracy``'s
input-mode latch) serialize as a JSON byte array (enums encoded by class
path + value), so a restored engine computes immediately — no "one
post-restore batch" warmup.

Shard provenance (deferred-sync mesh engines): the state subtree is the
SHARD-STACKED arena — row ``k`` of every per-dtype buffer is shard ``k``'s
local state — and the meta carries ``mesh_sync="deferred"`` plus ``world``
(the shard count). The merged global view is derivable from the locals
(``Metric.merge_stacked_states``) but not vice versa, and exact kill/resume
replay REQUIRES the locals: on resume each shard must continue from exactly
the rows it had folded. ``engine/pipeline.py::restore`` uses the provenance
to pick the restore path (verbatim same-world restore / host merge into a
step-sync or single-device engine / shard-0 embedding the other way).
"""
import hashlib
import importlib
import json
import os
import pickle
import shutil
import time
from enum import Enum
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from metrics_tpu.engine.faults import SnapshotCorruptError
from metrics_tpu.utils.imports import _ORBAX_AVAILABLE

__all__ = [
    "SnapshotCorruptError",
    "generations",
    "latest_snapshot",
    "load_snapshot",
    "save_snapshot",
]

_LATEST = "LATEST"


def _use_orbax() -> bool:
    """Whether saves go through orbax. Single-process only: orbax's
    checkpointer embeds its OWN cross-process barriers (``sync_global_
    processes`` keyed by the target path), and a fleet host's snapshot is a
    PER-HOST file — each host writes a different path at its own step count,
    so the embedded barrier would deadlock/assert across the fleet (ISSUE
    15). Under ``jax.distributed`` the pickle codec writes the piece instead
    — same payload tree, same integrity sidecar, loadable anywhere (the
    loader has always dispatched on dir-vs-file, so mixed codecs in one
    generation ring restore fine)."""
    if not _ORBAX_AVAILABLE:  # pragma: no cover - orbax is baked in here
        return False
    from metrics_tpu.utils.compat import distributed_client

    return distributed_client() is None


def _integrity_path(path: str) -> str:
    """Checksum sidecar for a snapshot: ``integrity_<name>.json`` next to it
    (NOT ``snap_``-prefixed — directory listings of snapshots must never
    mistake a sidecar for a generation)."""
    return os.path.join(os.path.dirname(path), f"integrity_{os.path.basename(path)}.json")


def _encode_host_attr(v: Any) -> Any:
    """JSON-able encoding of one host-derived attribute value. Enums (e.g.
    ``DataType``) carry their class path so decode restores the REAL enum
    member, not a lookalike string; ndarrays and tuples round-trip typed.
    A value outside the supported set raises with the offending type named —
    better a loud error at declaration-test time than a sticky dispatcher
    failure at the first snapshot boundary in production."""
    if isinstance(v, Enum):
        return {"__enum__": [type(v).__module__, type(v).__qualname__], "value": v.value}
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return {"__ndarray__": v.tolist(), "dtype": v.dtype.str}
    if isinstance(v, tuple):
        return {"__tuple__": [_encode_host_attr(x) for x in v]}
    if isinstance(v, list):
        return [_encode_host_attr(x) for x in v]
    if isinstance(v, (bool, int, float, str, type(None))):
        return v
    raise TypeError(
        f"host-derived compute attr of type {type(v).__name__} is not snapshot-"
        "serializable; supported: scalars, strings, None, enums, tuples/lists, ndarrays"
    )


def _decode_host_attr(v: Any) -> Any:
    if isinstance(v, dict) and "__enum__" in v:
        module, qualname = v["__enum__"]
        cls: Any = importlib.import_module(module)
        for part in qualname.split("."):
            cls = getattr(cls, part)
        return cls(v["value"])
    if isinstance(v, dict) and "__ndarray__" in v:
        return np.asarray(v["__ndarray__"], np.dtype(v["dtype"]))
    if isinstance(v, dict) and "__tuple__" in v:
        return tuple(_decode_host_attr(x) for x in v["__tuple__"])
    if isinstance(v, list):
        return [_decode_host_attr(x) for x in v]
    return v


def _host_attrs_to_bytes(attrs: Dict[str, Any]) -> np.ndarray:
    doc = json.dumps({k: _encode_host_attr(v) for k, v in attrs.items()})
    return np.frombuffer(doc.encode("utf-8"), np.uint8).copy()


def _host_attrs_from_bytes(buf: Any) -> Dict[str, Any]:
    doc = json.loads(bytes(np.asarray(buf, np.uint8)).decode("utf-8"))
    return {k: _decode_host_attr(v) for k, v in doc.items()}


def _payload_digest(payload: Any) -> str:
    """sha256 over a canonical serialization of the snapshot payload.

    Computed on the host-side numpy payload at SAVE time and re-derived from
    the DESERIALIZED payload at load time — so it catches silent value
    corruption (bit flips that still deserialize) in addition to the
    truncations the deserializer itself rejects. Canonical form: treedef
    repr + per-leaf (dtype, shape, raw bytes) for arrays, typed repr for
    scalars/strings — stable across the orbax and pickle codecs."""
    h = hashlib.sha256()
    leaves, treedef = jax.tree_util.tree_flatten(payload)
    h.update(repr(treedef).encode())
    for leaf in leaves:
        # strings BEFORE the numpy branch: codecs may hand back np.str_
        # (both a str and an np.generic) — normalize to the python value
        if isinstance(leaf, str):
            h.update(f"s:str:{str(leaf)!r}".encode())
        elif isinstance(leaf, (bytes, bytearray)):
            h.update(b"b:")
            h.update(bytes(leaf))
        elif isinstance(leaf, (np.ndarray, np.generic)):
            arr = np.asarray(leaf)
            h.update(f"a:{arr.dtype.str}:{arr.shape}".encode())
            h.update(np.ascontiguousarray(arr).tobytes())
        elif isinstance(leaf, (bool, int, float, type(None))):
            h.update(f"s:{type(leaf).__name__}:{leaf!r}".encode())
        else:  # pragma: no cover - payloads are numpy/scalars by construction
            h.update(f"o:{leaf!r}"[:256].encode())
    return h.hexdigest()


def _to_numpy_tree(state: Any) -> Any:
    return jax.tree.map(lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, state)


def _to_jax_tree(state: Any) -> Any:
    import jax.numpy as jnp

    return jax.tree.map(lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x, state)


def save_snapshot(
    directory: str,
    state: Any,
    meta: Dict[str, Any],
    keep: int = 2,
    host_attrs: Optional[Dict[str, Any]] = None,
) -> str:
    """Write one complete snapshot and atomically advance ``LATEST``.

    ``state`` is the engine's accumulated metric-state pytree — either the
    logical per-leaf tree or a packed arena dict (one array per dtype); the
    loader returns whichever was saved, verbatim. ``meta`` is a flat dict of
    ints/floats/strings (the step counter and friends); ``host_attrs`` is the
    metric's host-derived compute-attribute dict (JSON-encoded into the
    payload, returned under ``meta["host_attrs"]`` on load). Returns the
    snapshot's path. Keeps the newest ``keep`` snapshots, GCs the rest.
    """
    os.makedirs(directory, exist_ok=True)
    step = int(meta.get("step", 0))
    # the name must be UNIQUE, not just step-keyed: after reset()/a restart
    # replaying from batch 0, the same step comes around again — reusing the
    # name would delete-and-rewrite the very directory LATEST points to, and
    # a kill mid-rewrite would break the "LATEST always targets a COMPLETE
    # snapshot" guarantee. The nanosecond suffix keeps names fresh while
    # preserving step-order under the lexicographic sort GC relies on.
    name = f"snap_{step:012d}_{time.time_ns():016x}"
    payload = {
        "state": _to_numpy_tree(state),
        "meta": {k: np.asarray(v) if isinstance(v, (int, float)) else v for k, v in meta.items()},
    }
    if host_attrs:
        payload["host_attrs"] = _host_attrs_to_bytes(host_attrs)
    path = os.path.join(directory, name)
    if _use_orbax():
        import orbax.checkpoint as ocp

        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(os.path.abspath(path), payload, force=True)
    else:
        with open(path, "wb") as f:
            pickle.dump(payload, f)
    # integrity sidecar AFTER the payload, BEFORE the pointer: a kill between
    # payload and sidecar leaves an unreferenced generation (fallback loads
    # accept a missing sidecar); LATEST never points at an unverifiable one
    with open(_integrity_path(path), "w") as f:
        json.dump({"sha256": _payload_digest(payload)}, f)
    # the payload is durable; only now may the pointer move (atomic replace)
    tmp = os.path.join(directory, _LATEST + ".tmp")
    with open(tmp, "w") as f:
        f.write(name)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(directory, _LATEST))
    _gc(directory, keep)
    return path


def _gc(directory: str, keep: int) -> None:
    latest = latest_snapshot(directory)
    # "newest" means CREATION order (the ns suffix), NOT step order: after a
    # reset()/replay the step counter goes backwards, and sorting by the
    # step-prefixed name would protect stale pre-reset snapshots forever
    # while GC-ing the fresh ones down to LATEST's target alone
    snaps = sorted(
        (n for n in os.listdir(directory) if n.startswith("snap_")),
        key=lambda n: n.rsplit("_", 1)[-1],
    )
    for n in snaps[:-keep] if keep > 0 else []:
        if latest is not None and os.path.join(directory, n) == latest:
            continue  # never GC the pointer's target
        full = os.path.join(directory, n)
        shutil.rmtree(full, ignore_errors=True) if os.path.isdir(full) else os.unlink(full)
        integrity = _integrity_path(full)
        if os.path.exists(integrity):
            os.unlink(integrity)


def latest_snapshot(directory: str) -> Optional[str]:
    """Path of the newest COMPLETE snapshot, or None."""
    pointer = os.path.join(directory, _LATEST)
    if not os.path.exists(pointer):
        return None
    with open(pointer) as f:
        name = f.read().strip()
    path = os.path.join(directory, name)
    return path if os.path.exists(path) else None


def generations(directory: str) -> List[str]:
    """Every retained snapshot path under ``directory``, newest-first by
    CREATION order (the nanosecond suffix — step numbers recur after a
    reset/replay). This is the generation ring the fallback restore walks."""
    try:
        names = os.listdir(directory)
    except (FileNotFoundError, NotADirectoryError):
        return []
    snaps = [n for n in names if n.startswith("snap_")]
    return [
        os.path.join(directory, n)
        for n in sorted(snaps, key=lambda n: n.rsplit("_", 1)[-1], reverse=True)
    ]


def _load_verified(path: str, verify: bool = True) -> Any:
    """Deserialize + integrity-check one snapshot payload. Every failure mode
    of a rotten payload — truncation, bit flips the codec rejects, bit flips
    it silently accepts — surfaces as one typed :class:`SnapshotCorruptError`
    naming the path and generation."""
    generation = os.path.basename(path)
    if not os.path.exists(path):
        # an ABSENT snapshot is not a corrupt one: callers handling the
        # documented "no snapshot yet" contract catch FileNotFoundError.
        # (A path that exists but is missing internal files still wraps as
        # corruption below — that IS a rotten payload.)
        raise FileNotFoundError(f"no snapshot at {path}")
    try:
        if _ORBAX_AVAILABLE and os.path.isdir(path):
            import orbax.checkpoint as ocp

            with ocp.PyTreeCheckpointer() as ckptr:
                payload = ckptr.restore(os.path.abspath(path))
        else:
            with open(path, "rb") as f:
                payload = pickle.load(f)
        if not isinstance(payload, dict) or "state" not in payload or "meta" not in payload:
            raise SnapshotCorruptError(path, generation=generation, reason="payload is not a snapshot dict")
    except SnapshotCorruptError:
        raise
    except Exception as e:
        raise SnapshotCorruptError(
            path,
            generation=generation,
            reason=f"deserialization failed: {type(e).__name__}: {e}",
        ) from e
    integrity = _integrity_path(path)
    if verify and os.path.exists(integrity):
        try:
            with open(integrity) as f:
                want = json.load(f)["sha256"]
        except Exception as e:
            raise SnapshotCorruptError(
                path, generation=generation, reason="unreadable integrity sidecar"
            ) from e
        got = _payload_digest(payload)
        if got != want:
            raise SnapshotCorruptError(
                path,
                generation=generation,
                reason=f"checksum mismatch (want {want[:12]}…, got {got[:12]}…)",
            )
    return payload


def load_snapshot(
    directory_or_path: str, fallback: bool = False, verify: bool = True
) -> Tuple[Any, Dict[str, Any]]:
    """Load ``(state, meta)`` from a snapshot dir (follows ``LATEST``) or an
    explicit snapshot path. Raises ``FileNotFoundError`` when none exists.

    With ``fallback=True`` (directory form only) a corrupt/truncated payload
    does not end recovery: the generation ring is walked newest-first past
    every :class:`SnapshotCorruptError` to the newest VALID generation —
    ``meta["generations_skipped"]`` counts what was skipped and
    ``meta["snapshot_path"]`` names what actually loaded. Raises the last
    corruption error when every generation is rotten. ``verify=False`` skips
    the checksum (deserialization errors still surface typed)."""
    path = directory_or_path
    skipped = 0
    if os.path.isdir(path) and not os.path.basename(path).startswith("snap_"):
        latest = latest_snapshot(path)
        ring = generations(path)
        if latest is None and not (fallback and ring):
            raise FileNotFoundError(f"no complete snapshot under {path}")
        candidates = [latest] if latest is not None else []
        if fallback:
            candidates += [p for p in ring if p != latest]
        payload, path = None, None
        last_err: Optional[SnapshotCorruptError] = None
        for cand in candidates:
            try:
                payload = _load_verified(cand, verify=verify)
                path = cand
                break
            except SnapshotCorruptError as e:
                if not fallback:
                    raise
                skipped += 1
                last_err = e
        if payload is None:
            assert last_err is not None
            raise last_err
    else:
        payload = _load_verified(path, verify=verify)
    meta = {
        k: (int(v) if isinstance(v, np.ndarray) and v.dtype.kind in "iu" else v)
        for k, v in payload["meta"].items()
    }
    if "host_attrs" in payload:
        meta["host_attrs"] = _host_attrs_from_bytes(payload["host_attrs"])
    meta["snapshot_path"] = path
    meta["generations_skipped"] = skipped
    return _to_jax_tree(payload["state"]), meta
