"""Ragged-serving bench: ``python -m metrics_tpu.engine.ragged_bench``.

The pinned protocol behind ``BENCH.ragged_serving`` (ISSUE 17), run by
``bench.py`` in a subprocess with an 8-device virtual CPU mesh. One run
produces every ratio, so no number is stitched across environments:

* Zipfian QUERY cardinality (``engine/traffic.py``): G=512 query groups,
  240 batches under Zipf(alpha=1.05) — the hot query owns hundreds of rows,
  the tail one or two, exactly the skew a retrieval serving tier sees;
* the group-keyed traffic serves through a deferred-mesh ``RaggedEngine``
  (capacity sized to the observed hot-group maximum) — ingest rows/s,
  queries/s (distinct groups with value-in-hand over the full
  ingest+aggregate wall), and the aggregate ``result()`` latency;
* the EAGER HOST LOOP baseline — the reference pattern, one
  ``metric.update()`` per batch then ``compute()`` — runs in the same
  process on the same traffic: the served/eager wall ratio is
  ratios-in-one-run;
* zero steady-state compiles ASSERTED: a ``reset()`` + full replay of the
  same plan must add no AOT misses (the grouped program set is closed).

Absolute rates on the virtual CPU mesh are host-noise-bound → the entry
carries ``liveness_only``; the durable facts are the compile assertion, the
served-vs-eager value agreement, and the capacity/occupancy shape of the
Zipfian law (docs/benchmarking.md).
"""
import json
import sys
import time

NUM_DEVICES = 8
GROUPS = 512
N_BATCHES = 240
BUCKETS = (8, 24)


def run() -> dict:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from metrics_tpu import RetrievalMAP
    from metrics_tpu.engine import AotCache, EngineConfig, RaggedEngine
    from metrics_tpu.engine.traffic import zipf_traffic
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < NUM_DEVICES:
        return {"error": f"need {NUM_DEVICES} devices, have {len(devs)}"}
    mesh = Mesh(np.asarray(devs[:NUM_DEVICES]), ("dp",))

    traffic = zipf_traffic(GROUPS, N_BATCHES, alpha=1.05, seed=23)
    rows_per_group = np.zeros(GROUPS, np.int64)
    total_rows = 0
    for gid, p, _ in traffic:
        rows_per_group[gid] += p.shape[0]
        total_rows += p.shape[0]
    hot = int(rows_per_group.max())
    capacity = 1 << int(np.ceil(np.log2(max(2, hot))))
    groups_touched = int((rows_per_group > 0).sum())

    # ---- served: deferred-mesh ragged engine, one scalar-keyed submit per batch
    cache = AotCache()
    eng = RaggedEngine(
        RetrievalMAP(), num_groups=GROUPS,
        config=EngineConfig(buckets=BUCKETS, mesh=mesh, axis="dp",
                            mesh_sync="deferred"),
        capacity=capacity, aot_cache=cache,
    )
    with eng:
        t0 = time.perf_counter()
        for gid, p, t in traffic:
            eng.submit(gid, p, t.astype(np.float32))
        eng.flush()
        ingest_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        served_value = float(eng.result())
        result_s = time.perf_counter() - t0
        # steady-state: the SAME plan replayed through reset() must compile
        # nothing — the grouped program set is closed (hard assertion, the
        # acceptance criterion)
        warm = cache.misses
        eng.reset()
        for gid, p, t in traffic[:60]:
            eng.submit(gid, p, t.astype(np.float32))
        eng.flush()
        steady_compiles = cache.misses - warm
    if steady_compiles != 0:
        return {"error": f"steady-state replay compiled {steady_compiles} programs"}
    served_wall = ingest_s + result_s

    # ---- eager host loop baseline (the reference pattern), same process
    m = RetrievalMAP()
    t0 = time.perf_counter()
    for gid, p, t in traffic:
        m.update(jnp.asarray(p), jnp.asarray(t),
                 indexes=jnp.full((p.shape[0],), gid, jnp.int32))
    eager_value = float(m.compute())
    eager_wall = time.perf_counter() - t0

    return {
        "value": round(groups_touched / served_wall, 1),
        "unit": (
            f"queries/s (G={GROUPS} Zipf groups, {NUM_DEVICES}-dev virtual "
            "mesh, ingest+aggregate wall)"
        ),
        "vs_baseline": round(eager_wall / served_wall, 3),
        "ingest_rows_per_s": round(total_rows / ingest_s, 1),
        "aggregate_result_s": round(result_s, 3),
        "eager_host_loop_s": round(eager_wall, 3),
        "served_wall_s": round(served_wall, 3),
        "served_value": served_value,
        "eager_value": eager_value,
        "value_abs_diff": abs(served_value - eager_value),
        "groups": GROUPS,
        "groups_touched": groups_touched,
        "rows": total_rows,
        "capacity": capacity,
        "hot_group_rows": hot,
        "steady_compiles_after_warmup": int(steady_compiles),
        "protocol": (
            f"{N_BATCHES} Zipf(alpha=1.05, seed=23) batches over G={GROUPS} "
            f"query groups, capacity={capacity} (pow2 >= hot-group {hot}); "
            "served = deferred-mesh RaggedEngine ingest + aggregate result(); "
            "baseline = eager per-batch update loop + compute in the SAME "
            "process; ratios-in-one-run; reset()+replay asserts zero compiles"
        ),
        "liveness_only": True,
        "note": (
            "virtual CPU mesh timeshares one host: absolute rates are topology "
            "liveness; the durable facts are steady_compiles_after_warmup == 0, "
            "the served/eager value agreement, and the Zipf capacity shape"
        ),
    }


def main() -> int:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    print(json.dumps(run()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
