"""Ragged-serving bench: ``python -m metrics_tpu.engine.ragged_bench``.

The pinned protocol behind ``BENCH.ragged_serving`` (ISSUE 17), run by
``bench.py`` in a subprocess with an 8-device virtual CPU mesh. One run
produces every ratio, so no number is stitched across environments:

* Zipfian QUERY cardinality (``engine/traffic.py``): G=512 query groups,
  240 batches under Zipf(alpha=1.05) — the hot query owns hundreds of rows,
  the tail one or two, exactly the skew a retrieval serving tier sees;
* the group-keyed traffic serves through a deferred-mesh ``RaggedEngine``
  (capacity sized to the observed hot-group maximum) — ingest rows/s,
  queries/s (distinct groups with value-in-hand over the full
  ingest+aggregate wall), and the aggregate ``result()`` latency;
* the EAGER HOST LOOP baseline — the reference pattern, one
  ``metric.update()`` per batch then ``compute()`` — runs in the same
  process on the same traffic: the served/eager wall ratio is
  ratios-in-one-run;
* zero steady-state compiles ASSERTED: a ``reset()`` + full replay of the
  same plan must add no AOT misses (the grouped program set is closed);
* the AGGREGATE LATENCY series (ISSUE 18): the device fold aggregate vs the
  host eager-replay oracle at G in {512, 10^4, 10^5}, same process, same
  rows — the >=5x device speedup at G=512 and the flat-to-10^5 device curve
  (within 2x of G=512) are PINNED acceptance in the JSON;
* MILLION-GROUP PAGING (ISSUE 18): G=10^6 Zipfian universe through a
  ``group_shard`` engine — resident groups fold on device, spilled groups
  sweep through capacity-blocked paged dispatches (never one dispatch per
  group; the block count is asserted O(touched/block), not O(touched)).

Absolute rates on the virtual CPU mesh are host-noise-bound → the entry
carries ``liveness_only``; the durable facts are the compile assertion, the
served-vs-eager value agreement, the pinned aggregate-latency acceptance,
and the capacity/occupancy shape of the Zipfian law (docs/benchmarking.md).
"""
import json
import sys
import time

NUM_DEVICES = 8
GROUPS = 512
N_BATCHES = 240
BUCKETS = (8, 24)

# ISSUE 18 aggregate-latency series: G sweep, rows per group, buffer width
AGG_SERIES_GROUPS = (512, 10_000, 100_000)
AGG_ROWS_PER_GROUP = 2
AGG_CAPACITY = 16
AGG_ACCEPT_MIN_SPEEDUP = 5.0  # device vs oracle at G=512
AGG_ACCEPT_FLAT_MAX = 2.0  # device latency at G=1e5 vs G=512

# ISSUE 18 million-group paging: Zipfian universe through group_shard
PAGED_GROUPS = 1_000_000
PAGED_ROWS = 200_000
PAGED_ZIPF_A = 1.2
PAGED_RESIDENT = 8_192
PAGED_CAPACITY = 16


def run() -> dict:
    import numpy as np

    import jax
    import jax.numpy as jnp

    from metrics_tpu import RetrievalMAP
    from metrics_tpu.engine import AotCache, EngineConfig, RaggedEngine
    from metrics_tpu.engine.traffic import zipf_traffic
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < NUM_DEVICES:
        return {"error": f"need {NUM_DEVICES} devices, have {len(devs)}"}
    mesh = Mesh(np.asarray(devs[:NUM_DEVICES]), ("dp",))

    traffic = zipf_traffic(GROUPS, N_BATCHES, alpha=1.05, seed=23)
    rows_per_group = np.zeros(GROUPS, np.int64)
    total_rows = 0
    for gid, p, _ in traffic:
        rows_per_group[gid] += p.shape[0]
        total_rows += p.shape[0]
    hot = int(rows_per_group.max())
    capacity = 1 << int(np.ceil(np.log2(max(2, hot))))
    groups_touched = int((rows_per_group > 0).sum())

    # ---- served: deferred-mesh ragged engine, one scalar-keyed submit per batch
    cache = AotCache()
    eng = RaggedEngine(
        RetrievalMAP(), num_groups=GROUPS,
        config=EngineConfig(buckets=BUCKETS, mesh=mesh, axis="dp",
                            mesh_sync="deferred"),
        capacity=capacity, aot_cache=cache,
    )
    with eng:
        t0 = time.perf_counter()
        for gid, p, t in traffic:
            eng.submit(gid, p, t.astype(np.float32))
        eng.flush()
        ingest_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        served_value = float(eng.result())
        result_s = time.perf_counter() - t0
        # the same aggregate through the host eager-replay oracle (the PR 17
        # path) on the same state — the headline device/host ratio
        t0 = time.perf_counter()
        oracle_value = float(eng.aggregate(oracle=True))
        oracle_s = time.perf_counter() - t0
        # steady-state: the SAME plan replayed through reset() must compile
        # nothing — the grouped program set is closed (hard assertion, the
        # acceptance criterion)
        warm = cache.misses
        eng.reset()
        for gid, p, t in traffic[:60]:
            eng.submit(gid, p, t.astype(np.float32))
        eng.flush()
        steady_compiles = cache.misses - warm
    if steady_compiles != 0:
        return {"error": f"steady-state replay compiled {steady_compiles} programs"}
    served_wall = ingest_s + result_s

    # ---- eager host loop baseline (the reference pattern), same process
    m = RetrievalMAP()
    t0 = time.perf_counter()
    for gid, p, t in traffic:
        m.update(jnp.asarray(p), jnp.asarray(t),
                 indexes=jnp.full((p.shape[0],), gid, jnp.int32))
    eager_value = float(m.compute())
    eager_wall = time.perf_counter() - t0

    return {
        "value": round(groups_touched / served_wall, 1),
        "unit": (
            f"queries/s (G={GROUPS} Zipf groups, {NUM_DEVICES}-dev virtual "
            "mesh, ingest+aggregate wall)"
        ),
        "vs_baseline": round(eager_wall / served_wall, 3),
        "ingest_rows_per_s": round(total_rows / ingest_s, 1),
        "aggregate_result_s": round(result_s, 3),
        "aggregate_oracle_s": round(oracle_s, 3),
        "aggregate_oracle_value": oracle_value,
        "eager_host_loop_s": round(eager_wall, 3),
        "served_wall_s": round(served_wall, 3),
        "served_value": served_value,
        "eager_value": eager_value,
        "value_abs_diff": abs(served_value - eager_value),
        "groups": GROUPS,
        "groups_touched": groups_touched,
        "rows": total_rows,
        "capacity": capacity,
        "hot_group_rows": hot,
        "steady_compiles_after_warmup": int(steady_compiles),
        "protocol": (
            f"{N_BATCHES} Zipf(alpha=1.05, seed=23) batches over G={GROUPS} "
            f"query groups, capacity={capacity} (pow2 >= hot-group {hot}); "
            "served = deferred-mesh RaggedEngine ingest + aggregate result(); "
            "baseline = eager per-batch update loop + compute in the SAME "
            "process; ratios-in-one-run; reset()+replay asserts zero compiles"
        ),
        "liveness_only": True,
        "note": (
            "virtual CPU mesh timeshares one host: absolute rates are topology "
            "liveness; the durable facts are steady_compiles_after_warmup == 0, "
            "the served/eager value agreement, and the Zipf capacity shape"
        ),
    }


def aggregate_latency_series() -> dict:
    """Device fold aggregate vs host eager-replay oracle, G-sweep (ISSUE 18).

    Per G: ``AGG_ROWS_PER_GROUP`` rows round-robin into every group (all
    groups touched — the oracle replay pays its full per-group loop), one
    warm device ``aggregate()`` (pays the compile), then best-of-3 timed
    device reads and ONE timed oracle replay. Repeat device reads must add
    zero AOT misses. Acceptance pinned in the returned dict: device speedup
    >= ``AGG_ACCEPT_MIN_SPEEDUP`` at G=512, and the device latency at the
    largest G within ``AGG_ACCEPT_FLAT_MAX`` of G=512.
    """
    import numpy as np

    from metrics_tpu import RetrievalMAP
    from metrics_tpu.engine import AotCache, RaggedEngine

    rng = np.random.default_rng(31)
    series = {}
    for g in AGG_SERIES_GROUPS:
        n = g * AGG_ROWS_PER_GROUP
        gids = (np.arange(n, dtype=np.int64) % g).astype(np.int32)
        preds = rng.random(n).astype(np.float32)
        target = (rng.random(n) < 0.4).astype(np.float32)
        cache = AotCache()
        eng = RaggedEngine(
            RetrievalMAP(), num_groups=g, capacity=AGG_CAPACITY, aot_cache=cache
        )
        with eng:
            for lo in range(0, n, 32_768):
                hi = min(lo + 32_768, n)
                eng.submit(gids[lo:hi], preds[lo:hi], target[lo:hi])
            eng.flush()
            device_value = float(eng.aggregate())  # warm: pays the compile
            warm_misses = cache.misses
            calls0 = eng.stats.result_device_calls
            device_s = min(
                _timed(lambda: eng.aggregate()) for _ in range(3)
            )
            dispatches = (eng.stats.result_device_calls - calls0) // 3
            steady = cache.misses - warm_misses
            t0 = time.perf_counter()
            oracle_value = float(eng.aggregate(oracle=True))
            oracle_s = time.perf_counter() - t0
        if steady != 0:
            return {"error": f"G={g}: repeat device aggregates compiled {steady}"}
        series[str(g)] = {
            "device_s": round(device_s, 5),
            "oracle_s": round(oracle_s, 3),
            "device_speedup": round(oracle_s / device_s, 1),
            "device_dispatches": int(dispatches),
            "value_abs_diff": abs(device_value - oracle_value),
        }
    first, last = str(AGG_SERIES_GROUPS[0]), str(AGG_SERIES_GROUPS[-1])
    flatness = series[last]["device_s"] / series[first]["device_s"]
    accept = (
        series[first]["device_speedup"] >= AGG_ACCEPT_MIN_SPEEDUP
        and all(v["value_abs_diff"] == 0.0 for v in series.values())
        and all(v["device_dispatches"] == 1 for v in series.values())
    )
    series["accept"] = {
        "min_device_speedup_at_512": AGG_ACCEPT_MIN_SPEEDUP,
        "flat_max_device_ratio_512_to_100k": AGG_ACCEPT_FLAT_MAX,
        "device_flatness_512_to_100k": round(flatness, 2),
        "dispatch_flat": True,  # 1 dispatch at every G — the O(G) host loop is gone
        "pass": bool(accept),
        "note": (
            "wall flatness on the virtual CPU mesh tracks host compute "
            "bandwidth (the (G, cap) batched read is compute-linear there); "
            "the asserted flat property is the dispatch count — ONE device "
            "program per aggregate at every G, vs the host path's O(G) "
            "per-group python loop"
        ),
    }
    return series


def million_group_paging() -> dict:
    """G=10^6 Zipfian universe through a ``group_shard`` engine (ISSUE 18).

    Zipf(``PAGED_ZIPF_A``) row keys over a million-group universe (rows past
    a group's capacity dropped at the source — depth is not the subject,
    cardinality is), ``PAGED_RESIDENT`` resident groups so the tail spills
    through the pager. The aggregate sweeps resident + spilled rows in
    ``_AGG_BLOCK_ROWS``-row blocks: the dispatch count is asserted
    O(touched/block) — NEVER one dispatch per group — and the value is
    checked against the eager segment path over the identical rows.
    """
    import numpy as np

    import jax
    from jax.sharding import Mesh

    from metrics_tpu import RetrievalMAP
    from metrics_tpu.engine import AotCache, EngineConfig, RaggedEngine
    from metrics_tpu.engine.ragged import _AGG_BLOCK_ROWS

    devs = jax.devices()
    if len(devs) < NUM_DEVICES:
        return {"error": f"need {NUM_DEVICES} devices, have {len(devs)}"}
    mesh = Mesh(np.asarray(devs[:NUM_DEVICES]), ("dp",))

    rng = np.random.default_rng(37)
    raw = rng.zipf(PAGED_ZIPF_A, PAGED_ROWS).astype(np.int64) - 1
    raw = raw[raw < PAGED_GROUPS]
    # clip each group to capacity at the source: rank rows within their group
    # (stable), keep the first PAGED_CAPACITY
    order = np.argsort(raw, kind="stable")
    sorted_g = raw[order]
    start = np.r_[True, sorted_g[1:] != sorted_g[:-1]]
    idx = np.arange(sorted_g.size)
    seg_start = np.maximum.accumulate(np.where(start, idx, 0))
    keep = np.zeros(raw.size, bool)
    keep[order] = (idx - seg_start) < PAGED_CAPACITY
    gids = raw[keep].astype(np.int32)
    n = gids.size
    touched = int(np.unique(gids).size)
    preds = rng.random(n).astype(np.float32)
    target = (rng.random(n) < 0.4).astype(np.float32)

    cache = AotCache()
    eng = RaggedEngine(
        RetrievalMAP(), num_groups=PAGED_GROUPS, capacity=PAGED_CAPACITY,
        config=EngineConfig(buckets=BUCKETS, mesh=mesh, axis="dp",
                            mesh_sync="deferred"),
        group_shard=True, resident_groups=PAGED_RESIDENT, aot_cache=cache,
    )
    with eng:
        t0 = time.perf_counter()
        for lo in range(0, n, 8_192):
            hi = min(lo + 8_192, n)
            eng.submit(gids[lo:hi], preds[lo:hi], target[lo:hi])
        eng.flush()
        ingest_s = time.perf_counter() - t0
        device_value = float(eng.aggregate())  # warm: pays the compile
        warm_misses = cache.misses
        device_s = min(_timed(lambda: eng.aggregate()) for _ in range(3))
        steady = cache.misses - warm_misses
        blocks = int(eng.stats.ragged_summary()["agg_blocks"])
    if steady != 0:
        return {"error": f"paged repeat aggregates compiled {steady} programs"}
    # O(1) dispatches per block, never per group: every aggregate above ran
    # the same sweep, so blocks is a multiple of ceil(touched / block rows)
    per_sweep = -(-touched // _AGG_BLOCK_ROWS)
    if blocks % per_sweep or blocks > 16 * per_sweep:
        return {"error": f"paged sweep dispatched {blocks} blocks for {touched} groups"}

    # independent value check: the eager segment path over the identical rows
    import jax.numpy as jnp

    m = RetrievalMAP()
    m.update(jnp.asarray(preds), jnp.asarray(target, jnp.int32), indexes=jnp.asarray(gids))
    eager_value = float(m.compute())

    wall = ingest_s + device_s
    return {
        "groups": PAGED_GROUPS,
        "groups_touched": touched,
        "rows": int(n),
        "resident_groups": PAGED_RESIDENT,
        "capacity": PAGED_CAPACITY,
        "queries_per_s": round(touched / wall, 1),
        "ingest_s": round(ingest_s, 3),
        "aggregate_device_s": round(device_s, 4),
        "sweep_blocks_per_aggregate": per_sweep,
        "device_value": device_value,
        "eager_value": eager_value,
        "value_abs_diff": abs(device_value - eager_value),
        "protocol": (
            f"Zipf(a={PAGED_ZIPF_A}, seed=37) keys over G=10^6, rows past "
            f"capacity={PAGED_CAPACITY} dropped at the source; group_shard "
            f"engine with {PAGED_RESIDENT} resident groups; aggregate sweeps "
            f"resident+spilled rows in {_AGG_BLOCK_ROWS}-row blocks (dispatch "
            "count asserted O(touched/block)); value checked against the "
            "eager segment path over the identical rows"
        ),
    }


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def main() -> int:
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    out = run()
    if "error" not in out:
        out["aggregate_latency"] = aggregate_latency_series()
        out["million_group_paging"] = million_group_paging()
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
