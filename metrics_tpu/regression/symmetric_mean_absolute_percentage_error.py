"""SymmetricMeanAbsolutePercentageError module metric.

Parity: reference
``torchmetrics/regression/symmetric_mean_absolute_percentage_error.py:26``.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.symmetric_mean_absolute_percentage_error import (
    _symmetric_mean_absolute_percentage_error_compute,
    _symmetric_mean_absolute_percentage_error_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class SymmetricMeanAbsolutePercentageError(Metric):
    """Symmetric mean absolute percentage error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SymmetricMeanAbsolutePercentageError
        >>> target = jnp.asarray([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.asarray([3.0, 5.0, 2.5, 7.0])
        >>> metric = SymmetricMeanAbsolutePercentageError()
        >>> print(f"{float(metric(preds, target)):.4f}")
        0.1942
    """
    is_differentiable = True
    higher_is_better = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_abs_per_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_abs_per_error, num_obs = _symmetric_mean_absolute_percentage_error_update(
            jnp.asarray(preds), jnp.asarray(target)
        )
        self.sum_abs_per_error = self.sum_abs_per_error + sum_abs_per_error
        self.total = self.total + num_obs

    def compute(self) -> Array:
        return _symmetric_mean_absolute_percentage_error_compute(self.sum_abs_per_error, self.total)
