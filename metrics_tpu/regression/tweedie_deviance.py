"""TweedieDevianceScore module metric.

Parity: reference ``torchmetrics/regression/tweedie_deviance.py:26``.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.tweedie_deviance import (
    _tweedie_deviance_score_compute,
    _tweedie_deviance_score_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class TweedieDevianceScore(Metric):
    """Tweedie deviance score for the given ``power`` (0=normal, 1=poisson, 2=gamma).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import TweedieDevianceScore
        >>> target = jnp.asarray([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.asarray([3.0, 5.0, 2.5, 7.0])
        >>> deviance = TweedieDevianceScore(power=1.0)
        >>> print(f"{float(deviance(preds, target)):.4f}")
        0.2462
    """
    is_differentiable = True
    higher_is_better = False

    def __init__(self, power: float = 0.0, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        if 0 < power < 1:
            raise ValueError(f"Deviance Score is not defined for power={power}.")
        self.power = power
        self.add_state("sum_deviance_score", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("num_observations", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, targets: Array) -> None:
        sum_deviance_score, num_observations = _tweedie_deviance_score_update(
            jnp.asarray(preds), jnp.asarray(targets), self.power
        )
        self.sum_deviance_score = self.sum_deviance_score + sum_deviance_score
        self.num_observations = self.num_observations + num_observations

    def compute(self) -> Array:
        return _tweedie_deviance_score_compute(self.sum_deviance_score, self.num_observations)
