"""R2Score module metric.

Parity: reference ``torchmetrics/regression/r2.py:23``.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.r2 import _r2_score_compute, _r2_score_update
from metrics_tpu.metric import Metric

Array = jax.Array


class R2Score(Metric):
    """R² coefficient of determination (with adjusted/multioutput options).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import R2Score
        >>> target = jnp.asarray([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.asarray([3.0, 5.0, 2.5, 7.0])
        >>> metric = R2Score()
        >>> print(f"{float(metric(preds, target)):.4f}")
        0.7838
    """
    is_differentiable = True
    higher_is_better = True

    def __init__(
        self,
        num_outputs: int = 1,
        adjusted: int = 0,
        multioutput: str = "uniform_average",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.num_outputs = num_outputs

        if adjusted < 0 or not isinstance(adjusted, int):
            raise ValueError("`adjusted` parameter should be an integer larger or equal to 0.")
        self.adjusted = adjusted

        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(
                f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}"
            )
        self.multioutput = multioutput

        self.add_state("sum_squared_error", default=jnp.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("sum_error", default=jnp.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("residual", default=jnp.zeros(num_outputs), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_obs, sum_obs, rss, n_obs = _r2_score_update(jnp.asarray(preds), jnp.asarray(target))
        self.sum_squared_error = self.sum_squared_error + sum_squared_obs
        self.sum_error = self.sum_error + sum_obs
        self.residual = self.residual + rss
        self.total = self.total + n_obs

    def compute(self) -> Array:
        return _r2_score_compute(
            self.sum_squared_error, self.sum_error, self.residual, self.total, self.adjusted, self.multioutput
        )
