"""ExplainedVariance module metric.

Parity: reference ``torchmetrics/regression/explained_variance.py:26``.
"""
from typing import Any, Sequence, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.explained_variance import (
    _explained_variance_compute,
    _explained_variance_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class ExplainedVariance(Metric):
    """Explained variance (1 - Var[target - preds] / Var[target]).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import ExplainedVariance
        >>> target = jnp.asarray([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.asarray([3.0, 5.0, 2.5, 7.0])
        >>> metric = ExplainedVariance()
        >>> print(f"{float(metric(preds, target)):.4f}")
        0.8456
    """
    is_differentiable = True
    higher_is_better = True

    def __init__(self, multioutput: str = "uniform_average", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_multioutput = ("raw_values", "uniform_average", "variance_weighted")
        if multioutput not in allowed_multioutput:
            raise ValueError(
                f"Invalid input to argument `multioutput`. Choose one of the following: {allowed_multioutput}"
            )
        self.multioutput = multioutput
        self.add_state("sum_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_squared_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_target", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("sum_squared_target", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("n_obs", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target = _explained_variance_update(
            jnp.asarray(preds), jnp.asarray(target)
        )
        self.n_obs = self.n_obs + n_obs
        self.sum_error = self.sum_error + sum_error
        self.sum_squared_error = self.sum_squared_error + sum_squared_error
        self.sum_target = self.sum_target + sum_target
        self.sum_squared_target = self.sum_squared_target + sum_squared_target

    def compute(self) -> Union[Array, Sequence[Array]]:
        return _explained_variance_compute(
            self.n_obs,
            self.sum_error,
            self.sum_squared_error,
            self.sum_target,
            self.sum_squared_target,
            self.multioutput,
        )
