"""PearsonCorrCoef module metric — the custom-merge (dist_reduce_fx=None) archetype.

Parity: reference ``torchmetrics/regression/pearson.py:56`` (states at :112-117,
device-merge ``_final_aggregation`` at :24-53). After a mesh sync the stats arrive
stacked ``(world, ...)`` and are folded with the Chan parallel-statistics formula at
compute — the state-pattern-4 template from SURVEY.md §2.4.
"""
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.pearson import (
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


def _final_aggregation(
    means_x: Array,
    means_y: Array,
    vars_x: Array,
    vars_y: Array,
    corrs_xy: Array,
    nbs: Array,
) -> Tuple[Array, Array, Array, Array]:
    """Fold per-device streaming statistics with the Chan et al. parallel formula.

    Parity target: reference ``pearson.py:24-53``. Deviation: the reference's merge
    rescales var/corr sums as if they were normalised (a known upstream bug, fixed in
    later torchmetrics releases); since the accumulated states here are exact *sums*
    of squared deviations / cross products, the correct merge is the plain Chan
    update: M2 = M2_1 + M2_2 + n1*n2/nb * (m1-m2)^2 (and the cross-product analogue).
    The loop is over the (static) world size, so this traces fine under jit.
    """
    mx1, my1, vx1, vy1, cxy1, n1 = means_x[0], means_y[0], vars_x[0], vars_y[0], corrs_xy[0], nbs[0]
    for i in range(1, means_x.shape[0]):
        mx2, my2, vx2, vy2, cxy2, n2 = means_x[i], means_y[i], vars_x[i], vars_y[i], corrs_xy[i], nbs[i]
        nb = n1 + n2
        mean_x = (n1 * mx1 + n2 * mx2) / nb
        mean_y = (n1 * my1 + n2 * my2) / nb
        w = (n1 * n2) / nb
        var_x = vx1 + vx2 + w * (mx1 - mx2) ** 2
        var_y = vy1 + vy2 + w * (my1 - my2) ** 2
        corr_xy = cxy1 + cxy2 + w * (mx1 - mx2) * (my1 - my2)
        mx1, my1, vx1, vy1, cxy1, n1 = mean_x, mean_y, var_x, var_y, corr_xy, nb
    return vx1, vy1, cxy1, n1


class PearsonCorrCoef(Metric):
    """Pearson correlation coefficient via streaming mean/var/cov statistics with the Chan parallel merge across devices.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import PearsonCorrCoef
        >>> target = jnp.asarray([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.asarray([3.0, 5.0, 2.5, 7.0])
        >>> metric = PearsonCorrCoef()
        >>> print(f"{float(metric(preds, target)):.4f}")
        0.9202
    """
    is_differentiable = True
    higher_is_better = None

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("mean_x", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("mean_y", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("var_x", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("var_y", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("corr_xy", default=jnp.asarray(0.0), dist_reduce_fx=None)
        self.add_state("n_total", default=jnp.asarray(0.0), dist_reduce_fx=None)

    def update(self, preds: Array, target: Array) -> None:
        preds = jnp.asarray(preds, dtype=jnp.float32) if not jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating) else jnp.asarray(preds)
        target = jnp.asarray(target, dtype=preds.dtype) if not jnp.issubdtype(jnp.asarray(target).dtype, jnp.floating) else jnp.asarray(target)
        self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total = _pearson_corrcoef_update(
            preds, target, self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
        )

    # forward() must snapshot/restore: the streaming stats merge jointly (Chan
    # formula over the full state), not leaf-by-leaf
    full_state_update = True

    def compute(self) -> Array:
        if self.mean_x.ndim > 0 and self.mean_x.shape[0] > 1:
            # post-sync: stats stacked (world, ...) -> fold with Chan formula
            var_x, var_y, corr_xy, n_total = _final_aggregation(
                self.mean_x, self.mean_y, self.var_x, self.var_y, self.corr_xy, self.n_total
            )
        else:
            var_x, var_y, corr_xy, n_total = self.var_x, self.var_y, self.corr_xy, self.n_total
        return _pearson_corrcoef_compute(var_x, var_y, corr_xy, n_total)


class PearsonCorrcoef(PearsonCorrCoef):
    """Deprecated alias. Parity: reference ``regression/pearson.py:145-168``
    (renamed to ``PearsonCorrCoef`` in v0.7, removal scheduled for v0.8)."""

    def __init__(self, **kwargs: Any) -> None:
        rank_zero_warn(
            "`PearsonCorrcoef` was renamed to `PearsonCorrCoef` and it will be removed.",
            DeprecationWarning,
        )
        super().__init__(**kwargs)
