"""CosineSimilarity module metric.

Parity: reference ``torchmetrics/regression/cosine_similarity.py:24`` (cat states).
"""
from typing import Any, Optional

import jax

from metrics_tpu.functional.regression.cosine_similarity import (
    _cosine_similarity_compute,
    _cosine_similarity_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class CosineSimilarity(Metric):
    """Cosine similarity between prediction and target vectors.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CosineSimilarity
        >>> preds = jnp.asarray([[1.0, 0.0], [0.0, 1.0]])
        >>> target = jnp.asarray([[1.0, 0.0], [1.0, 1.0]])
        >>> cosine = CosineSimilarity(reduction="mean")
        >>> print(f"{float(cosine(preds, target)):.4f}")
        0.8536
    """
    is_differentiable = True
    higher_is_better = True

    def __init__(self, reduction: Optional[str] = "sum", **kwargs: Any) -> None:
        super().__init__(**kwargs)
        allowed_reduction = ("sum", "mean", "none", None)
        if reduction not in allowed_reduction:
            raise ValueError(f"Expected argument `reduction` to be one of {allowed_reduction} but got {reduction}")
        self.reduction = reduction
        self.add_state("preds", [], dist_reduce_fx="cat")
        self.add_state("target", [], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = _cosine_similarity_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _cosine_similarity_compute(preds, target, self.reduction)
