"""SpearmanCorrCoef module metric.

Parity: reference ``torchmetrics/regression/spearman.py:26`` (cat states :80-81,
ranking at compute).
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.spearman import (
    _spearman_corrcoef_compute,
    _spearman_corrcoef_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class SpearmanCorrCoef(Metric):
    """Spearman rank correlation (tie-aware ranking at compute).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import SpearmanCorrCoef
        >>> target = jnp.asarray([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.asarray([3.0, 5.0, 2.5, 7.0])
        >>> metric = SpearmanCorrCoef()
        >>> print(f"{float(metric(preds, target)):.4f}")
        0.8000
    """
    is_differentiable = False
    higher_is_better = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        rank_zero_warn(
            "Metric `SpearmanCorrcoef` will save all targets and predictions in the buffer."
            " For large datasets, this may lead to large memory footprint."
        )
        self.add_state("preds", default=[], dist_reduce_fx="cat")
        self.add_state("target", default=[], dist_reduce_fx="cat")

    def update(self, preds: Array, target: Array) -> None:
        preds, target = jnp.asarray(preds), jnp.asarray(target)
        # same contract as the functional: integer inputs raise (reference
        # behavior), sub-f32 floats widen so both APIs rank in f32
        if jnp.issubdtype(preds.dtype, jnp.floating) and preds.dtype not in (jnp.float32, jnp.float64):
            preds = preds.astype(jnp.float32)
        if jnp.issubdtype(target.dtype, jnp.floating) and target.dtype not in (jnp.float32, jnp.float64):
            target = target.astype(jnp.float32)
        preds, target = _spearman_corrcoef_update(preds, target)
        self.preds.append(preds)
        self.target.append(target)

    def compute(self) -> Array:
        preds = dim_zero_cat(self.preds)
        target = dim_zero_cat(self.target)
        return _spearman_corrcoef_compute(preds, target)


class SpearmanCorrcoef(SpearmanCorrCoef):
    """Deprecated alias. Parity: reference ``regression/spearman.py`` (renamed
    to ``SpearmanCorrCoef`` in v0.7, removal scheduled for v0.8)."""

    def __init__(self, **kwargs: Any) -> None:
        rank_zero_warn(
            "`SpearmanCorrcoef` was renamed to `SpearmanCorrCoef` and it will be removed.",
            DeprecationWarning,
        )
        super().__init__(**kwargs)
