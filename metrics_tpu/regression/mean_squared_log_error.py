"""MeanSquaredLogError module metric.

Parity: reference ``torchmetrics/regression/mean_squared_log_error.py:26``.
"""
from typing import Any

import jax
import jax.numpy as jnp

from metrics_tpu.functional.regression.mean_squared_log_error import (
    _mean_squared_log_error_compute,
    _mean_squared_log_error_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class MeanSquaredLogError(Metric):
    """Mean squared logarithmic error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import MeanSquaredLogError
        >>> target = jnp.asarray([2.5, 5.0, 4.0, 8.0])
        >>> preds = jnp.asarray([3.0, 5.0, 2.5, 7.0])
        >>> metric = MeanSquaredLogError()
        >>> print(f"{float(metric(preds, target)):.4f}")
        0.0397
    """
    is_differentiable = True
    higher_is_better = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("sum_squared_log_error", default=jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", default=jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: Array, target: Array) -> None:
        sum_squared_log_error, n_obs = _mean_squared_log_error_update(jnp.asarray(preds), jnp.asarray(target))
        self.sum_squared_log_error = self.sum_squared_log_error + sum_squared_log_error
        self.total = self.total + n_obs

    def compute(self) -> Array:
        return _mean_squared_log_error_compute(self.sum_squared_log_error, self.total)
