"""MetricCollection: dict-of-metrics with shared call signature and fused sync.

Parity: reference ``torchmetrics/metric_collections.py:26-235`` (forward :103,
update :112, add_metrics :149, items/keys(keep_base) :205-221, prefix/postfix, clone).

Beyond parity (the headline TPU win): the functional path
``init_state / update_state / compute_synced`` carries ALL member metrics' states as
one pytree and syncs them in a single fused collective bundle
(``parallel.collectives.fused_axis_sync``) — one psum for every counter state of every
member, where the reference issues O(metrics x states) sequential all_gathers
(``metric.py:240-245``).
"""
import weakref
from copy import deepcopy
from typing import Any, Dict, Hashable, Iterable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import _EAGER_ONLY, _FORWARD_JIT_CACHE, _MISS, Metric, _jit_cache_lookup
from metrics_tpu.parallel.collectives import AxisSpec, fused_axis_sync, in_mapped_context
from metrics_tpu.parallel.mesh import current_metric_axis
from metrics_tpu.utils.checks import deferred_value_checks
from metrics_tpu.utils.data import dim_zero_cat


class MetricCollection(dict):
    """An ordered dict of metrics sharing one call signature.

    Args:
        metrics: a Metric, a sequence of Metrics, or a dict name->Metric.
        prefix/postfix: added to every key in the output dict.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import Accuracy, MeanSquaredError, MetricCollection
        >>> metrics = MetricCollection([Accuracy(), MeanSquaredError()])
        >>> preds = jnp.asarray([0.0, 1.0, 0.0, 0.0])
        >>> target = jnp.asarray([1, 1, 0, 0])
        >>> {k: f"{float(v):.4f}" for k, v in metrics(preds, target).items()}
        {'Accuracy': '0.7500', 'MeanSquaredError': '0.2500'}
    """

    def __init__(
        self,
        metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]],
        *additional_metrics: Metric,
        prefix: Optional[str] = None,
        postfix: Optional[str] = None,
    ) -> None:
        super().__init__()
        self.prefix = self._check_arg(prefix, "prefix")
        self.postfix = self._check_arg(postfix, "postfix")
        self.add_metrics(metrics, *additional_metrics)

    @staticmethod
    def _check_arg(arg: Optional[str], name: str) -> Optional[str]:
        if arg is None or isinstance(arg, str):
            return arg
        raise ValueError(f"Expected input `{name}` to be a string, but got {type(arg)}")

    def add_metrics(
        self, metrics: Union[Metric, Sequence[Metric], Dict[str, Metric]], *additional_metrics: Metric
    ) -> None:
        """Parity: reference ``metric_collections.py:149-203``."""
        if isinstance(metrics, Metric):
            metrics = [metrics]
        if isinstance(metrics, Sequence):
            remain: list = []
            for m in additional_metrics:
                (metrics if isinstance(m, Metric) else remain).append(m)
            if remain:
                raise ValueError(
                    f"You have passes extra arguments {remain} which are not `Metric` so they will be ignored."
                )
        elif additional_metrics:
            raise ValueError(
                f"You have passes extra arguments {additional_metrics} which are not compatible with first passed dictionary."
            )
        if isinstance(metrics, dict):
            for name in sorted(metrics.keys()):
                metric = metrics[name]
                if not isinstance(metric, Metric):
                    raise ValueError(
                        f"Value {metric} belonging to key {name} is not an instance of `metrics_tpu.Metric`"
                    )
                self[name] = metric
        elif isinstance(metrics, Sequence):
            for metric in metrics:
                if not isinstance(metric, Metric):
                    raise ValueError(f"Input {metric} to `MetricCollection` is not a instance of `metrics_tpu.Metric`")
                name = type(metric).__name__
                if name in self:
                    raise ValueError(f"Encountered two metrics both named {name}")
                self[name] = metric
        else:
            raise ValueError("Unknown input to MetricCollection.")

    # ------------------------------------------------------------------- eager facade

    def forward(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        """Call every member; returns dict of per-batch values. Parity: ``:103-110``.

        When every member is trace-safe, the whole collection compiles into ONE
        XLA executable (all members' update→merge→compute(delta) fused — the
        eager-facade twin of the fused ``update_state``/``sync_states`` path);
        otherwise falls back to the per-member loop, where each member still
        uses its own compiled forward if it can.
        """
        fast = self._forward_fused(args, kwargs)
        if fast is not _MISS:
            return fast
        return {self._set_name(k): m(*args, **m._filter_kwargs(**kwargs)) for k, m in self.items(keep_base=True)}

    def __call__(self, *args: Any, **kwargs: Any) -> Dict[str, Any]:
        return self.forward(*args, **kwargs)

    def _forward_fused(self, args: Any, kwargs: Any):
        """Fused compiled forward (same per-signature protocol as
        ``Metric._forward_fast``: 1st call eager for validation, 2nd compiles,
        untraceable collections permanently fall back). Returns the renamed
        value dict or ``_MISS``."""
        members = list(self.items(keep_base=True))
        if not members:
            return _MISS
        for _, m in members:
            if m.dist_sync_on_step or m.dist_sync_fn is not None or not m._defaults or m._is_synced:
                return _MISS
            if not m._states_mergeable:
                # full_state_update members need the snapshot/double-update path
                # (Metric.forward gates on this BEFORE its fast path — so must we)
                return _MISS
            path_ok = getattr(m, "_fwd_path_ok", None)
            if path_ok is None:
                path_ok = m._forward_jit_safe() and not m._has_list_state()
                m._fwd_path_ok = path_ok
            if not path_ok:
                return _MISS
        parsed = Metric._forward_signature(args, kwargs)
        if parsed is None:
            return _MISS
        inner_sig, array_idx, leaves = parsed
        # membership identity + each member's baked compute_on_step key the trace
        sig = (inner_sig, tuple((k, id(m), bool(m.compute_on_step)) for k, m in members))
        entry, cache = _jit_cache_lookup(self, sig, lambda: self._build_fused_step(inner_sig, array_idx, leaves))
        if entry is None:
            return _MISS
        try:
            states = {k: m._pack_state() for k, m in members}
            merged, values, codes = entry(states, [leaves[i] for i in array_idx])
        except Exception:
            cache[sig] = _EAGER_ONLY
            return _MISS
        out: Dict[str, Any] = {}
        for k, m in members:
            m._load_state(merged[k])
            m._mark_updated()
            val = values[k] if m.compute_on_step else None
            m._forward_cache = val
            m._deferred_errcode = (
                codes[k] if m._deferred_errcode is None else jnp.maximum(m._deferred_errcode, codes[k])
            )
            out[self._set_name(k)] = val
        return out

    def _build_fused_step(self, inner_sig: Any, array_idx: Sequence[int], leaves: Sequence[Any]):
        treedef = inner_sig[0]
        n_leaves = len(leaves)
        consts = {i: leaf for i, leaf in enumerate(leaves) if i not in array_idx}
        compute_on_step = {k: bool(m.compute_on_step) for k, m in self.items(keep_base=True)}
        # weak binding: the compiled step must not pin the collection (or its
        # members, reachable through it) in the jit cache
        wself = weakref.ref(self)

        def step(states: Dict[str, Dict[str, Any]], arrays: Sequence[Any]):
            coll = wself()
            assert coll is not None  # caller holds a strong ref for the call
            merged_leaves: List[Any] = [None] * n_leaves
            for i, arr in zip(array_idx, arrays):
                merged_leaves[i] = arr
            for i, c in consts.items():
                merged_leaves[i] = c
            a, kw = jax.tree_util.tree_unflatten(treedef, merged_leaves)
            merged: Dict[str, Any] = {}
            values: Dict[str, Any] = {}
            codes: Dict[str, Any] = {}
            for k, m in coll.items(keep_base=True):
                with deferred_value_checks() as checks:
                    delta = m.update_state(m.init_state(), *a, **m._filter_kwargs(**kw))
                merged[k] = m.merge_states(states[k], delta)
                values[k] = m.compute_from(delta) if compute_on_step[k] else None
                codes[k] = checks.combined()
            return merged, values, codes

        return jax.jit(step)

    # identity hash AND identity eq (dict itself is unhashable; pinning only
    # hash would break the hash/eq invariant for value-equal collections):
    # needed to key the weak jit cache, and matches the reference where
    # MetricCollection is an nn.ModuleDict (identity semantics)
    __hash__ = object.__hash__
    __eq__ = object.__eq__
    __ne__ = object.__ne__

    def _invalidate_fused(self) -> None:
        """Membership changed: drop all fused traces (and their cache-budget slots)."""
        _FORWARD_JIT_CACHE.pop(self, None)

    def __setitem__(self, key: str, value: Metric) -> None:
        self._invalidate_fused()
        super().__setitem__(key, value)

    def __delitem__(self, key: str) -> None:
        self._invalidate_fused()
        super().__delitem__(key)

    def pop(self, *args: Any) -> Metric:
        self._invalidate_fused()
        return super().pop(*args)

    def popitem(self) -> Tuple[str, Metric]:
        self._invalidate_fused()
        return super().popitem()

    def clear(self) -> None:
        self._invalidate_fused()
        super().clear()

    def update(self, *args: Any, **kwargs: Any) -> None:
        for _, m in self.items(keep_base=True):
            m.update(*args, **m._filter_kwargs(**kwargs))

    def compute(self) -> Dict[str, Any]:
        return {self._set_name(k): m.compute() for k, m in self.items(keep_base=True)}

    def reset(self) -> None:
        for _, m in self.items(keep_base=True):
            m.reset()

    def clone(self, prefix: Optional[str] = None, postfix: Optional[str] = None) -> "MetricCollection":
        mc = deepcopy(self)
        if prefix:
            mc.prefix = self._check_arg(prefix, "prefix")
        if postfix:
            mc.postfix = self._check_arg(postfix, "postfix")
        return mc

    def persistent(self, mode: bool = True) -> None:
        for _, m in self.items(keep_base=True):
            m.persistent(mode)

    def state_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for k, m in self.items(keep_base=True):
            out.update(m.state_dict(prefix=f"{k}."))
        return out

    def load_state_dict(self, state_dict: Dict[str, Any]) -> None:
        for k, m in self.items(keep_base=True):
            m.load_state_dict(state_dict, prefix=f"{k}.")

    # -------------------------------------------------------- functional / fused path

    def init_state(self) -> Dict[str, Dict[str, Any]]:
        """One pytree holding all member states: {metric_name: state_dict}."""
        return {k: m.init_state() for k, m in self.items(keep_base=True)}

    def update_state(self, state: Dict[str, Dict[str, Any]], *args: Any, **kwargs: Any) -> Dict[str, Dict[str, Any]]:
        """Pure fan-out update of all members. Safe inside jit/scan/shard_map."""
        return {
            k: m.update_state(state[k], *args, **m._filter_kwargs(**kwargs))
            for k, m in self.items(keep_base=True)
        }

    def abstract_state(self) -> Dict[str, Dict[str, Any]]:
        """``ShapeDtypeStruct`` pytree mirroring :meth:`init_state` (AOT template)."""
        return {k: m.abstract_state() for k, m in self.items(keep_base=True)}

    def merge_states(
        self, a: Dict[str, Dict[str, Any]], b: Dict[str, Dict[str, Any]]
    ) -> Dict[str, Dict[str, Any]]:
        """Pairwise merge of two collection state pytrees (member-wise, pure)."""
        return {k: m.merge_states(a[k], b[k]) for k, m in self.items(keep_base=True)}

    def merge_stacked_states(
        self, stacked: Dict[str, Dict[str, Any]]
    ) -> Dict[str, Dict[str, Any]]:
        """Member-wise stack-axis merge (``Metric.merge_stacked_states``) —
        the deferred-sync mesh engine's boundary merge of shard-local states."""
        return {k: m.merge_stacked_states(stacked[k]) for k, m in self.items(keep_base=True)}

    def stacked_merge_unsupported_reason(self) -> "str | None":
        """None when every member's states fold by their ``dist_reduce_fx``
        across a stack axis (the deferred-sync mesh serving requirement)."""
        for k, m in self.items(keep_base=True):
            r = m.stacked_merge_unsupported_reason()
            if r is not None:
                return f"member {k!r}: {r}"
        return None

    def masked_update_unsupported_reason(self) -> "str | None":
        """None when every member supports the mask-aware update path."""
        for k, m in self.items(keep_base=True):
            r = m.masked_update_unsupported_reason()
            if r is not None:
                return f"member {k!r}: {r}"
        return None

    def masked_update_strategies(self) -> Dict[str, "str | None"]:
        """Per-member masked-update strategy (``Metric.masked_update_strategy``)
        — the serving observable for which members ride the vmapped delta path
        and which fall back to the sequential scan fold."""
        return {k: m.masked_update_strategy() for k, m in self.items(keep_base=True)}

    def update_state_masked(
        self, state: Dict[str, Dict[str, Any]], *args: Any, mask: Any, **kwargs: Any
    ) -> Dict[str, Dict[str, Any]]:
        """Mask-aware fan-out update of all members (the streaming-engine entry:
        one call == one fused program over every member's masked delta; members
        without a row-neutral reduction identity take their scan fallback
        INSIDE the same program — the compiled-program count is unchanged)."""
        return {
            k: m.update_state_masked(state[k], *args, mask=mask, **m._filter_kwargs(**kwargs))
            for k, m in self.items(keep_base=True)
        }

    def segmented_update_unsupported_reason(self) -> "str | None":
        """None when every member supports the multi-stream segmented update."""
        for k, m in self.items(keep_base=True):
            r = m.segmented_update_unsupported_reason()
            if r is not None:
                return f"member {k!r}: {r}"
        return None

    def update_state_segmented(
        self,
        state: Dict[str, Dict[str, Any]],
        *args: Any,
        mask: Any,
        segment_ids: Any,
        num_segments: int,
        **kwargs: Any,
    ) -> Dict[str, Dict[str, Any]]:
        """Multi-stream fan-out update: every member's stream-stacked state
        rows addressed by ``segment_ids`` take the row deltas (one fused
        program across all members — the ``MultiStreamEngine`` step)."""
        return {
            k: m.update_state_segmented(
                state[k], *args, mask=mask, segment_ids=segment_ids,
                num_segments=num_segments, **m._filter_kwargs(**kwargs),
            )
            for k, m in self.items(keep_base=True)
        }

    def arena_layout(self) -> Any:
        """Per-dtype packing plan over ALL member states (``engine/arena.py``):
        the whole collection's step dispatch carries one donated buffer per
        dtype class, however many members it serves."""
        from metrics_tpu.engine.arena import ArenaLayout

        return ArenaLayout.for_state(self.abstract_state())

    # ------------------------------------------------------- sync precision policy

    def set_sync_precision(
        self, spec: Union[str, Dict[str, Union[str, Dict[str, str]]]]
    ) -> "MetricCollection":
        """Declare the collection's quantized-sync policy (chainable). A
        blanket string fans out to every member (``Metric.set_sync_precision``
        semantics: eligible float-sum states quantize, counts/cat stay
        exact); a dict keyed by member name routes per-member specs."""
        if isinstance(spec, str):
            for _, m in self.items(keep_base=True):
                m.set_sync_precision(spec)
        elif isinstance(spec, dict):
            for name, sub in spec.items():
                if name not in self:
                    raise ValueError(f"no member named {name!r} in this collection")
                dict.__getitem__(self, name).set_sync_precision(sub)
        else:
            raise ValueError(
                f"sync_precision spec must be a string or a per-member dict, got {type(spec).__name__}"
            )
        return self

    def state_sync_precisions(self) -> Dict[str, str]:
        """Flat ``{member.state_path: precision}`` over every member."""
        out: Dict[str, str] = {}
        for k, m in self.items(keep_base=True):
            for path, prec in m.state_sync_precisions().items():
                out[f"{k}.{path}"] = prec
        return out

    def sync_precision_tag(self) -> str:
        """Policy tag for AOT program keys (see ``Metric.sync_precision_tag``
        — same shared implementation, so the two can never drift)."""
        from metrics_tpu.metric import sync_precision_tag_of

        return sync_precision_tag_of(self.state_sync_precisions())

    def sync_leaf_info(self) -> List[Any]:
        """Member-concatenated ``(fx, abstract_leaf, precision)`` triples —
        the payload-accounting/audit view (``Metric.sync_leaf_info``)."""
        out: List[Any] = []
        for _, m in self.items(keep_base=True):
            out.extend(m.sync_leaf_info())
        return out

    def sync_error_bounds(self, state: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
        """Per-member bounded-error oracle over a shard-stacked collection
        state (``Metric.sync_error_bounds``), keys prefixed by member name."""
        out: Dict[str, Any] = {}
        for k, m in self.items(keep_base=True):
            for path, bound in m.sync_error_bounds(state[k]).items():
                out[f"{k}.{path}"] = bound
        return out

    def host_compute_attrs(self) -> Dict[str, Any]:
        """Flat ``{member.path: value}`` of every member's host-derived
        compute attributes (``Metric.host_compute_attrs``)."""
        out: Dict[str, Any] = {}
        for k, m in self.items(keep_base=True):
            for a, v in m.host_compute_attrs().items():
                out[f"{k}.{a}"] = v
        return out

    def restore_host_compute_attrs(self, attrs: Dict[str, Any]) -> None:
        for k, m in self.items(keep_base=True):
            prefix = f"{k}."
            sub = {p[len(prefix):]: v for p, v in attrs.items() if p.startswith(prefix)}
            if sub:
                m.restore_host_compute_attrs(sub)

    def sync_states(
        self, state: Dict[str, Dict[str, Any]], axis_name: Optional[AxisSpec] = None
    ) -> Dict[str, Dict[str, Any]]:
        """Fused cross-axis sync of ALL member states in one collective bundle."""
        axis = axis_name or current_metric_axis()
        if axis is None or not in_mapped_context(axis):
            return state
        leaves: List[Tuple[Any, Any]] = []
        slots: List[Tuple[str, str]] = []
        precs: List[str] = []
        for k, m in self.items(keep_base=True):
            for sname in m._defaults:
                v = state[k][sname]
                was_list = isinstance(v, list)
                v = dim_zero_cat(v) if was_list else v
                fx = m._reductions[sname]
                # gathered list states stay FLATTENED (reference metric.py:249-252)
                leaves.append(("cat" if fx is None and was_list else fx, v))
                slots.append((k, sname))
                precs.append(
                    "exact" if was_list else m._sync_precision.get(sname, "exact")
                )
        synced = fused_axis_sync(leaves, axis, precisions=precs)
        out: Dict[str, Dict[str, Any]] = {k: {} for k, _ in self.items(keep_base=True)}
        for (k, sname), v in zip(slots, synced):
            out[k][sname] = v
        # wrapper/compositional members: their nested metrics' states sync
        # recursively with the children's own reductions
        for k, m in self.items(keep_base=True):
            if m._CHILD_KEY in state[k]:
                out[k][m._CHILD_KEY] = m._sync_child_states(state[k][m._CHILD_KEY], axis)
        return out

    def compute_from(self, state: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
        return {self._set_name(k): m.compute_from(state[k]) for k, m in self.items(keep_base=True)}

    def compute_synced(self, state: Dict[str, Dict[str, Any]], axis_name: Optional[AxisSpec] = None) -> Dict[str, Any]:
        return self.compute_from(self.sync_states(state, axis_name))

    # ------------------------------------------------------------------------- naming

    def _set_name(self, base: str) -> str:
        name = base if self.prefix is None else self.prefix + base
        name = name if self.postfix is None else name + self.postfix
        return name

    def _to_renamed_dict(self) -> Dict[str, Metric]:
        return {self._set_name(k): v for k, v in super().items()}

    def items(self, keep_base: bool = False) -> Iterable[Tuple[str, Metric]]:
        """Parity: reference ``metric_collections.py:205-213``."""
        if keep_base:
            return super().items()
        return self._to_renamed_dict().items()

    def keys(self, keep_base: bool = False) -> Iterable[str]:
        if keep_base:
            return super().keys()
        return self._to_renamed_dict().keys()

    def values(self) -> Iterable[Metric]:
        return super().values()

    def __getitem__(self, key: str) -> Metric:
        return dict.__getitem__(self, key)

    def __repr__(self) -> str:
        repr_str = self.__class__.__name__ + "("
        for k, v in self.items(keep_base=True):
            repr_str += f"\n  {k}: {repr(v)}"
        if self.prefix:
            repr_str += f"\n  prefix={self.prefix}"
        if self.postfix:
            repr_str += f"\n  postfix={self.postfix}"
        return repr_str + "\n)" if len(self) else repr_str + ")"
