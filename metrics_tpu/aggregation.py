"""Aggregation metrics: generic reducers usable as standalone metrics.

Parity: reference ``torchmetrics/aggregation.py:24-439`` (BaseAggregator, MaxMetric,
MinMetric, SumMetric, CatMetric, MeanMetric) including the nan_strategy
(error/warn/ignore/<float impute>) contract.

TPU note: nan handling is done with ``jnp.where`` masks (branch-free, trace-safe);
the 'error'/'warn' strategies need a host-side value check and therefore only run
eagerly — inside jit they degrade to 'ignore' with a one-time warning.
"""
from typing import Any, Callable, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.exceptions import MetricsTPUUserError
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class BaseAggregator(Metric):
    """Base for aggregation metrics. Parity: reference ``aggregation.py:24-109``."""

    value: Union[Array, List[Array]]
    is_differentiable = None
    higher_is_better = None

    def __init__(
        self,
        fn: Union[Callable, str],
        default_value: Union[Array, List],
        nan_strategy: Union[str, float] = "error",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        allowed_nan_strategy = ("error", "warn", "ignore")
        if nan_strategy not in allowed_nan_strategy and not isinstance(nan_strategy, (int, float)):
            raise ValueError(
                f"Arg `nan_strategy` should either be a float or one of {allowed_nan_strategy} but got {nan_strategy}."
            )
        self.nan_strategy = nan_strategy
        self.add_state("value", default=default_value, dist_reduce_fx=fn)

    def _forward_jit_safe(self) -> bool:
        # 'error'/'warn' must see concrete values on EVERY batch (raise/warn on
        # nan) — the compiled forward path would silently degrade them to 'ignore'
        return self.nan_strategy not in ("error", "warn") and super()._forward_jit_safe()

    def _cast_and_nan_check_input(self, x: Union[float, Array]) -> Array:
        """Convert input to float array and apply the NaN strategy."""
        x = jnp.asarray(x, dtype=jnp.float32) if not isinstance(x, jax.Array) else x.astype(jnp.float32)
        if self.nan_strategy in ("error", "warn"):
            if isinstance(jnp.sum(x), jax.core.Tracer):
                rank_zero_warn(
                    "nan_strategy='error'/'warn' cannot run inside jit; treating as 'ignore'.",
                    UserWarning,
                )
            else:
                contains_nan = bool(jnp.any(jnp.isnan(x)))
                if contains_nan and self.nan_strategy == "error":
                    raise RuntimeError("Encountered `nan` values in tensor")
                if contains_nan and self.nan_strategy == "warn":
                    rank_zero_warn("Encountered `nan` values in tensor. Will be removed.", UserWarning)
        return x

    def _nan_mask_or_impute(self, x: Array, neutral: float) -> Array:
        """Replace NaNs with the impute value or a reduction-neutral element."""
        fill = float(self.nan_strategy) if isinstance(self.nan_strategy, (int, float)) and not isinstance(
            self.nan_strategy, bool
        ) else neutral
        return jnp.where(jnp.isnan(x), jnp.asarray(fill, dtype=x.dtype), x)

    def update(self, value: Union[float, Array]) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def compute(self) -> Array:
        return self.value


class MaxMetric(BaseAggregator):
    """Running max. Parity: reference ``aggregation.py:112-174``.

    Example:
        >>> from metrics_tpu import MaxMetric
        >>> metric = MaxMetric()
        >>> for v in [1.0, 5.0, 3.0]:
        ...     metric.update(v)
        >>> print(f"{float(metric.compute()):.4f}")
        5.0000
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("max", jnp.asarray(-jnp.inf), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value = self._cast_and_nan_check_input(value)
        value = self._nan_mask_or_impute(value, -jnp.inf)
        if value.size:
            self.value = jnp.maximum(self.value, jnp.max(value))


class MinMetric(BaseAggregator):
    """Running min. Parity: reference ``aggregation.py:177-239``.

    Example:
        >>> from metrics_tpu import MinMetric
        >>> metric = MinMetric()
        >>> for v in [4.0, 2.0, 3.0]:
        ...     metric.update(v)
        >>> print(f"{float(metric.compute()):.4f}")
        2.0000
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("min", jnp.asarray(jnp.inf), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value = self._cast_and_nan_check_input(value)
        value = self._nan_mask_or_impute(value, jnp.inf)
        if value.size:
            self.value = jnp.minimum(self.value, jnp.min(value))


class SumMetric(BaseAggregator):
    """Running sum. Parity: reference ``aggregation.py:242-297``.

    Example:
        >>> from metrics_tpu import SumMetric
        >>> metric = SumMetric()
        >>> for v in [1.0, 2.0, 3.0]:
        ...     metric.update(v)
        >>> print(f"{float(metric.compute()):.4f}")
        6.0000
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0), nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value = self._cast_and_nan_check_input(value)
        value = self._nan_mask_or_impute(value, 0.0)
        if value.size:
            self.value = self.value + jnp.sum(value)


class CatMetric(BaseAggregator):
    """Concatenate all seen values. Parity: reference ``aggregation.py:300-360``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu import CatMetric
        >>> metric = CatMetric()
        >>> metric.update(jnp.asarray([1.0]))
        >>> metric.update(jnp.asarray([2.0]))
        >>> metric.compute().tolist()
        [1.0, 2.0]
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("cat", [], nan_strategy, **kwargs)

    def update(self, value: Union[float, Array]) -> None:
        value = jnp.atleast_1d(self._cast_and_nan_check_input(value))
        if isinstance(self.nan_strategy, (int, float)) and not isinstance(self.nan_strategy, str):
            value = self._nan_mask_or_impute(value, 0.0)
        elif not isinstance(jnp.sum(value), jax.core.Tracer):
            value = value[~jnp.isnan(value)]
        if value.size:
            self.value.append(value)

    def compute(self) -> Array:
        return dim_zero_cat(self.value) if self.value else jnp.zeros(0)


class MeanMetric(BaseAggregator):
    """Running (weighted) mean. Parity: reference ``aggregation.py:363-439``.

    Example:
        >>> from metrics_tpu import MeanMetric
        >>> metric = MeanMetric()
        >>> for v in [1.0, 2.0, 3.0]:
        ...     metric.update(v)
        >>> print(f"{float(metric.compute()):.4f}")
        2.0000
    """

    def __init__(self, nan_strategy: Union[str, float] = "warn", **kwargs: Any) -> None:
        super().__init__("sum", jnp.asarray(0.0), nan_strategy, **kwargs)
        self.add_state("weight", default=jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, value: Union[float, Array], weight: Union[float, Array] = 1.0) -> None:
        value = self._cast_and_nan_check_input(value)
        weight = self._cast_and_nan_check_input(weight)
        if value.size == 0:
            return
        weight = jnp.broadcast_to(weight, value.shape)
        nan = jnp.isnan(value)
        value = self._nan_mask_or_impute(value, 0.0)
        if not isinstance(self.nan_strategy, (int, float)) or isinstance(self.nan_strategy, bool):
            weight = jnp.where(nan, 0.0, weight)
        self.value = self.value + jnp.sum(value * weight)
        self.weight = self.weight + jnp.sum(weight)

    def compute(self) -> Array:
        return self.value / self.weight
