"""SQuAD module metric.

Parity: reference ``torchmetrics/text/squad.py:29``.
"""
from typing import Any, Dict

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.squad import (
    PREDS_TYPE,
    TARGETS_TYPE,
    _squad_compute,
    _squad_input_check,
    _squad_update,
)
from metrics_tpu.metric import Metric

Array = jax.Array


class SQuAD(Metric):
    """SQuAD v1.1 exact-match and token-F1 over prediction/answer dicts.

    Example:
        >>> from metrics_tpu import SQuAD
        >>> squad = SQuAD()
        >>> preds = [{"prediction_text": "berlin", "id": "q1"}]
        >>> refs = [{"answers": {"text": ["berlin"], "answer_start": [0]}, "id": "q1"}]
        >>> {k: float(v) for k, v in squad(preds, refs).items()}
        {'exact_match': 100.0, 'f1': 100.0}
    """
    is_differentiable = False
    higher_is_better = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("f1_score", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("exact_match", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0), dist_reduce_fx="sum")

    def update(self, preds: PREDS_TYPE, targets: TARGETS_TYPE) -> None:
        preds_dict, targets_list = _squad_input_check(preds, targets)
        f1, exact_match, total = _squad_update(preds_dict, targets_list)
        self.f1_score = self.f1_score + f1
        self.exact_match = self.exact_match + exact_match
        self.total = self.total + total

    def compute(self) -> Dict[str, Array]:
        return _squad_compute(self.f1_score, self.exact_match, self.total)
