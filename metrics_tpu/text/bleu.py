"""BLEUScore module metric.

Parity: reference ``torchmetrics/text/bleu.py:29`` (states :92-95: n-gram
numerator/denominator + length counters, all sum-reduced — one fused psum on sync).
"""
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update
from metrics_tpu.metric import Metric

Array = jax.Array


class BLEUScore(Metric):
    """BLEU score (n-gram precision with brevity penalty) over a translation corpus.

    Example:
        >>> from metrics_tpu import BLEUScore
        >>> preds = ["the cat sat on the mat"]
        >>> refs = [["a cat sat on the mat", "the cat sits on the mat"]]
        >>> bleu = BLEUScore()
        >>> print(f"{float(bleu(preds, refs)):.4f}")
        0.8409
    """
    is_differentiable = False
    higher_is_better = True

    def __init__(self, n_gram: int = 4, smooth: bool = False, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.n_gram = n_gram
        self.smooth = smooth
        self.add_state("trans_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("ref_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("numerator", jnp.zeros(n_gram), dist_reduce_fx="sum")
        self.add_state("denominator", jnp.zeros(n_gram), dist_reduce_fx="sum")

    def update(self, translate_corpus: Sequence[str], reference_corpus: Sequence[Sequence[str]]) -> None:
        translate_corpus = [translate_corpus] if isinstance(translate_corpus, str) else translate_corpus
        reference_corpus = [
            [ref] if isinstance(ref, str) else ref for ref in reference_corpus
        ]
        self.trans_len, self.ref_len, self.numerator, self.denominator = _bleu_score_update(
            translate_corpus, reference_corpus, self.numerator, self.denominator,
            self.trans_len, self.ref_len, self.n_gram,
        )

    def compute(self) -> Array:
        return _bleu_score_compute(
            self.trans_len, self.ref_len, self.numerator, self.denominator, self.n_gram, self.smooth
        )
