"""WordInfoLost module metric.

Parity: reference ``torchmetrics/text/wil.py:23``.
"""
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.wil import _wil_compute, _wil_update
from metrics_tpu.metric import Metric

Array = jax.Array


class WordInfoLost(Metric):
    """Word information lost (1 - hits²/(pred words × ref words)).

    Example:
        >>> from metrics_tpu import WordInfoLost
        >>> metric = WordInfoLost()
        >>> score = metric(['hello there world'], ['hello there word'])
        >>> print(f"{float(score):.4f}")
        0.5556
    """
    is_differentiable = False
    higher_is_better = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("reference_total", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("prediction_total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, predictions: Union[str, List[str]], references: Union[str, List[str]]) -> None:
        errors, reference_total, prediction_total = _wil_update(predictions, references)
        self.errors = self.errors + errors
        self.reference_total = self.reference_total + reference_total
        self.prediction_total = self.prediction_total + prediction_total

    def compute(self) -> Array:
        return _wil_compute(self.errors, self.reference_total, self.prediction_total)
