"""WordErrorRate module metric (+ deprecated WER alias).

Parity: reference ``torchmetrics/text/wer.py:24,106`` (states :83-84).
"""
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.wer import _wer_compute, _wer_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.prints import rank_zero_warn

Array = jax.Array


class WordErrorRate(Metric):
    """Word error rate (Levenshtein word edits / reference words; native C++ kernel).

    Example:
        >>> from metrics_tpu import WordErrorRate
        >>> metric = WordErrorRate()
        >>> score = metric(['hello there world'], ['hello there word'])
        >>> print(f"{float(score):.4f}")
        0.3333
    """
    is_differentiable = False
    higher_is_better = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, predictions: Union[str, List[str]], references: Union[str, List[str]]) -> None:
        errors, total = _wer_update(predictions, references)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        return _wer_compute(self.errors, self.total)


class WER(WordErrorRate):
    """Deprecated alias. Parity: reference ``wer.py:106``."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        rank_zero_warn("`WER` was renamed to `WordErrorRate` and it will be removed.", DeprecationWarning)
        super().__init__(*args, **kwargs)
