"""CHRFScore module metric.

Parity: reference ``torchmetrics/text/chrf.py:46`` (per-order count states, all
sum-reduced).
"""
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.chrf import _chrf_compute, _chrf_update
from metrics_tpu.functional.text.helper import _canonicalize_corpora
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class CHRFScore(Metric):
    """chrF / chrF++ score (character n-gram F-score).

    Example:
        >>> from metrics_tpu import CHRFScore
        >>> chrf = CHRFScore()
        >>> score = chrf(['the cat sat on the mat'], ['a cat sat on the mat'])
        >>> print(f"{float(score):.4f}")
        0.8719
    """
    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        n_char_order: int = 6,
        n_word_order: int = 2,
        beta: float = 2.0,
        lowercase: bool = False,
        whitespace: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if not isinstance(n_char_order, int) or n_char_order < 1:
            raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
        if not isinstance(n_word_order, int) or n_word_order < 0:
            raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
        if beta < 0:
            raise ValueError("Expected argument `beta` to be greater than 0.")
        self.n_char_order = n_char_order
        self.n_word_order = n_word_order
        self.beta = beta
        self.lowercase = lowercase
        self.whitespace = whitespace
        self.return_sentence_level_score = return_sentence_level_score

        n_order = n_char_order + n_word_order
        self.add_state("matching", jnp.zeros(n_order), dist_reduce_fx="sum")
        self.add_state("total_pred", jnp.zeros(n_order), dist_reduce_fx="sum")
        self.add_state("total_ref", jnp.zeros(n_order), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_chrf", [], dist_reduce_fx="cat")

    def update(self, hypothesis_corpus: Sequence[str], reference_corpus: Union[Sequence[str], Sequence[Sequence[str]]]) -> None:
        # arg names match the reference (``text/chrf.py:145``) for kwarg-routing parity
        preds, targets = _canonicalize_corpora(hypothesis_corpus, reference_corpus)
        sentence_scores: Optional[List[Array]] = [] if self.return_sentence_level_score else None
        self.matching, self.total_pred, self.total_ref = _chrf_update(
            preds, targets, self.matching, self.total_pred, self.total_ref,
            self.n_char_order, self.n_word_order, self.lowercase, self.whitespace, self.beta, sentence_scores,
        )
        if self.return_sentence_level_score and sentence_scores:
            self.sentence_chrf.append(jnp.stack(sentence_scores))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        score = _chrf_compute(self.matching, self.total_pred, self.total_ref, self.beta)
        if self.return_sentence_level_score:
            return score, dim_zero_cat(self.sentence_chrf)
        return score
