"""SacreBLEUScore module metric.

Parity: reference ``torchmetrics/text/sacre_bleu.py:34``.
"""
from typing import Any, Sequence

import jax

from metrics_tpu.functional.text.bleu import _bleu_score_update
from metrics_tpu.functional.text.sacre_bleu import _SacreBLEUTokenizer
from metrics_tpu.text.bleu import BLEUScore

Array = jax.Array


class SacreBLEUScore(BLEUScore):
    """BLEU with canonical sacrebleu tokenization.

    Example:
        >>> from metrics_tpu import SacreBLEUScore
        >>> preds = ["the cat sat on the mat"]
        >>> refs = [["a cat sat on the mat", "the cat sits on the mat"]]
        >>> sacre_bleu = SacreBLEUScore(tokenize="13a")
        >>> print(f"{float(sacre_bleu(preds, refs)):.4f}")
        0.8409
    """

    def __init__(
        self,
        n_gram: int = 4,
        smooth: bool = False,
        tokenize: str = "13a",
        lowercase: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(n_gram=n_gram, smooth=smooth, **kwargs)
        self.tokenizer = _SacreBLEUTokenizer(tokenize, lowercase)

    def update(self, translate_corpus: Sequence[str], reference_corpus: Sequence[Sequence[str]]) -> None:
        translate_corpus = [translate_corpus] if isinstance(translate_corpus, str) else translate_corpus
        reference_corpus = [[ref] if isinstance(ref, str) else ref for ref in reference_corpus]
        self.trans_len, self.ref_len, self.numerator, self.denominator = _bleu_score_update(
            translate_corpus, reference_corpus, self.numerator, self.denominator,
            self.trans_len, self.ref_len, self.n_gram, tokenizer=self.tokenizer,
        )
