"""CharErrorRate module metric.

Parity: reference ``torchmetrics/text/cer.py:24``.
"""
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.cer import _cer_compute, _cer_update
from metrics_tpu.metric import Metric

Array = jax.Array


class CharErrorRate(Metric):
    """Character error rate (Levenshtein character edits / reference characters).

    Example:
        >>> from metrics_tpu import CharErrorRate
        >>> metric = CharErrorRate()
        >>> score = metric(['hello there world'], ['hello there word'])
        >>> print(f"{float(score):.4f}")
        0.0625
    """
    is_differentiable = False
    higher_is_better = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, predictions: Union[str, List[str]], references: Union[str, List[str]]) -> None:
        errors, total = _cer_update(predictions, references)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        return _cer_compute(self.errors, self.total)
