"""WordInfoPreserved module metric.

Parity: reference ``torchmetrics/text/wip.py:23``.
"""
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.wip import _wip_compute, _wip_update
from metrics_tpu.metric import Metric

Array = jax.Array


class WordInfoPreserved(Metric):
    """Word information preserved (hits²/(pred words × ref words)).

    Example:
        >>> from metrics_tpu import WordInfoPreserved
        >>> metric = WordInfoPreserved()
        >>> score = metric(['hello there world'], ['hello there word'])
        >>> print(f"{float(score):.4f}")
        0.4444
    """
    is_differentiable = False
    higher_is_better = True

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("reference_total", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("prediction_total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, predictions: Union[str, List[str]], references: Union[str, List[str]]) -> None:
        errors, reference_total, prediction_total = _wip_update(predictions, references)
        self.errors = self.errors + errors
        self.reference_total = self.reference_total + reference_total
        self.prediction_total = self.prediction_total + prediction_total

    def compute(self) -> Array:
        return _wip_compute(self.errors, self.reference_total, self.prediction_total)
