"""TranslationEditRate module metric.

Parity: reference ``torchmetrics/text/ter.py:24``.
"""
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.helper import _canonicalize_corpora
from metrics_tpu.functional.text.ter import _ter_compute, _ter_update
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class TranslationEditRate(Metric):
    """Translation edit rate (edits / average reference length).

    Example:
        >>> from metrics_tpu import TranslationEditRate
        >>> ter = TranslationEditRate()
        >>> score = ter(['the cat sat on the mat'], [['a cat sat on the mat']])
        >>> print(f"{float(score):.4f}")
        0.1667
    """
    is_differentiable = False
    higher_is_better = False

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
        return_sentence_level_score: bool = False,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.normalize = normalize
        self.no_punctuation = no_punctuation
        self.lowercase = lowercase
        self.asian_support = asian_support
        self.return_sentence_level_score = return_sentence_level_score

        self.add_state("total_num_edits", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total_ref_len", jnp.asarray(0.0), dist_reduce_fx="sum")
        if return_sentence_level_score:
            self.add_state("sentence_ter", [], dist_reduce_fx="cat")

    def update(self, hypothesis_corpus: Sequence[str], reference_corpus: Sequence[Union[str, Sequence[str]]]) -> None:
        # arg names match the reference (``text/ter.py:105``) for kwarg-routing parity
        preds, targets = _canonicalize_corpora(hypothesis_corpus, reference_corpus)
        sentence_scores: Optional[List[Array]] = [] if self.return_sentence_level_score else None
        self.total_num_edits, self.total_ref_len = _ter_update(
            preds, targets, self.total_num_edits, self.total_ref_len,
            self.lowercase, self.normalize, self.no_punctuation, sentence_scores,
            self.asian_support,
        )
        if self.return_sentence_level_score and sentence_scores:
            self.sentence_ter.append(jnp.stack(sentence_scores))

    def compute(self) -> Union[Array, Tuple[Array, Array]]:
        score = _ter_compute(self.total_num_edits, self.total_ref_len)
        if self.return_sentence_level_score:
            return score, dim_zero_cat(self.sentence_ter)
        return score
