"""BERTScore module metric.

Parity: reference ``torchmetrics/text/bert.py:40`` (update :195 tokenizes and stores
token tensors as cat-states; compute :226 runs the embedding pipeline). The encoder
is pluggable (local HF Flax model / user forward fn) and shares the functional
path's jit-compiled, cached forward + fused scoring (``functional/text/bert.py``).
"""
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.bert import (
    _apply_baseline,
    _load_baseline_row,
    _resolve_baseline_path,
    _resolve_forward,
    _score_tokenized,
    _simple_whitespace_tokenizer,
)
from metrics_tpu.metric import Metric

Array = jax.Array


def _derive_length_buckets(max_length: int) -> Tuple[int, ...]:
    """Power-of-two token-length bucket edges up to (and including) max_length."""
    edges = []
    b = 8
    while b < max_length:
        edges.append(b)
        b *= 2
    edges.append(max_length)
    return tuple(edges)


def _bucket_pad_tokens(
    enc: Dict[str, np.ndarray], buckets: Sequence[int]
) -> Dict[str, np.ndarray]:
    """Pad the token-length dim up to the smallest bucket edge >= L.

    Score-invariant (attention masks exclude pad positions) but bounds the
    set of sequence lengths the encoder forward ever sees, so the jit trace
    cache stays O(len(buckets)) instead of one entry per distinct per-call
    batch max (the unbounded-compile bug this fixes).
    """
    ids = np.asarray(enc["input_ids"])
    mask = np.asarray(enc["attention_mask"])
    length = ids.shape[1]
    target = next((b for b in buckets if b >= length), length)
    if target > length:
        pad = ((0, 0), (0, target - length))
        ids = np.pad(ids, pad)
        mask = np.pad(mask, pad)
    return {"input_ids": ids, "attention_mask": mask}


def _cat_padded(chunks: List[Array], length: int) -> np.ndarray:
    """Concatenate (N_i, L_i) token chunks after right-padding each to ``length``."""
    out = []
    for c in chunks:
        c = np.asarray(c)
        if c.shape[1] < length:
            c = np.pad(c, ((0, 0), (0, length - c.shape[1])))
        out.append(c)
    return np.concatenate(out, axis=0)


class BERTScore(Metric):
    """BERTScore: greedy cosine matching of contextual embeddings (P/R/F1 per pair).

    Parity: reference ``text/bert.py:40``. Encoder is pluggable (local HF Flax
    checkpoint, flax module, or a user forward fn) — see ``functional.bert_score``.
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Any] = None,
        user_tokenizer: Optional[Any] = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        max_length: int = 128,
        batch_size: int = 64,
        num_threads: int = 4,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_path: Optional[str] = None,
        baseline_url: Optional[str] = None,
        mesh: Optional[Any] = None,
        mesh_axis: Any = "dp",
        model_host: Optional[Any] = None,
        length_buckets: Optional[Sequence[int]] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model_name_or_path = model_name_or_path
        self.max_length = max_length
        self.batch_size = batch_size
        self.idf = idf
        self.user_tokenizer = user_tokenizer
        self.rescale_with_baseline = rescale_with_baseline
        # token-length bucket edges: every _tokenize() pads to a bucket edge,
        # never the per-call batch max, so the encoder's trace cache is bounded
        # by len(length_buckets) rather than by the traffic's length diversity.
        self.length_buckets = (
            tuple(sorted(length_buckets)) if length_buckets is not None
            else _derive_length_buckets(max_length)
        )
        # load at construction so a bad baseline config (missing/malformed csv,
        # out-of-range num_layers) fails fast, and compute() does no file IO
        path = _resolve_baseline_path(rescale_with_baseline, baseline_path, baseline_url)
        self.baseline = _load_baseline_row(path, num_layers) if path is not None else None
        # resolve eagerly: a missing encoder should fail at construction.
        # mesh: the compute()-time encoder forward runs batch-parallel over the
        # mesh's data axis (sharded embedded-model path, parallel/embedded.py)
        self.forward_fn = _resolve_forward(user_forward_fn, model, model_name_or_path, mesh, mesh_axis)

        # model_host: serve the encoder forward from a resident ModelHost
        # (batch-bucketed, megabatch-coalesced, AOT-cached executables; shared
        # across metric instances with the same encoder) — engine/model_host.py.
        self.model_host = None
        if model_host is not None and model_host is not False:
            from metrics_tpu.engine.model_host import (
                ModelHost, ModelHostConfig, encoder_host,
            )

            if isinstance(model_host, ModelHost):
                host = model_host
            else:
                config = (
                    model_host if isinstance(model_host, ModelHostConfig)
                    else ModelHostConfig(mesh=mesh, mesh_axis=mesh_axis)
                )
                host = encoder_host(forward_fn=self.forward_fn, config=config)
            self.model_host = host

            def _host_forward(ids: Array, mask: Array) -> Array:
                return jnp.asarray(host.infer(ids, mask))

            # the host owns compilation; tell _resolve_forward/_embed not to
            # re-jit this callable (functional/text/bert.py honours the flag)
            _host_forward._metrics_tpu_prejitted = True
            self.forward_fn = _host_forward

        self.add_state("preds_input_ids", [], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", [], dist_reduce_fx="cat")
        self.add_state("target_input_ids", [], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", [], dist_reduce_fx="cat")

    def _tokenize(self, sentences: List[str]) -> Dict[str, np.ndarray]:
        if self.user_tokenizer is not None:
            enc = self.user_tokenizer(sentences, self.max_length)
        else:
            enc = _simple_whitespace_tokenizer(sentences, self.max_length)
        return _bucket_pad_tokens(enc, self.length_buckets)

    def update(self, predictions: List[str], references: List[str]) -> None:
        enc_pred = self._tokenize(predictions)
        enc_tgt = self._tokenize(references)
        self.preds_input_ids.append(jnp.asarray(enc_pred["input_ids"]))
        self.preds_attention_mask.append(jnp.asarray(enc_pred["attention_mask"]))
        self.target_input_ids.append(jnp.asarray(enc_tgt["input_ids"]))
        self.target_attention_mask.append(jnp.asarray(enc_tgt["attention_mask"]))

    def compute(self) -> Dict[str, List[float]]:
        # update() calls may have landed on different length buckets; pad every
        # chunk to the common max bucket edge so the whole compute runs at one
        # (already-bucketed) sequence length and the fused path stays eligible.
        length = max(
            [int(np.asarray(c).shape[1]) for c in self.preds_input_ids]
            + [int(np.asarray(c).shape[1]) for c in self.target_input_ids]
        )
        precision, recall, f1 = _score_tokenized(
            self.forward_fn,
            _cat_padded(self.preds_input_ids, length),
            _cat_padded(self.preds_attention_mask, length),
            _cat_padded(self.target_input_ids, length),
            _cat_padded(self.target_attention_mask, length),
            idf=self.idf,
            batch_size=self.batch_size,
            # reference contract strips [CLS]/[SEP] from matching (bert.py:324);
            # the whitespace fallback tokenizer adds no special tokens
            strip_special=self.user_tokenizer is not None,
        )
        if self.rescale_with_baseline:
            precision, recall, f1 = _apply_baseline(precision, recall, f1, self.baseline)
        return {
            "precision": [float(x) for x in precision],
            "recall": [float(x) for x in recall],
            "f1": [float(x) for x in f1],
        }
