"""BERTScore module metric.

Parity: reference ``torchmetrics/text/bert.py:40`` (update :195 tokenizes and stores
token tensors as cat-states; compute :226 runs the embedding pipeline). The encoder
is pluggable (local HF Flax model / user forward fn) — see
``functional/text/bert.py``.
"""
from typing import Any, Callable, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.bert import (
    _bert_score_from_embeddings,
    _get_tokens_idf,
    _idf_weights,
    _simple_whitespace_tokenizer,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class BERTScore(Metric):
    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Any] = None,
        user_tokenizer: Optional[Any] = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        max_length: int = 128,
        batch_size: int = 64,
        num_threads: int = 4,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_path: Optional[str] = None,
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model_name_or_path = model_name_or_path
        self.max_length = max_length
        self.batch_size = batch_size
        self.idf = idf
        self.user_tokenizer = user_tokenizer

        forward = user_forward_fn
        if forward is None and model is not None:
            forward = lambda ids, mask: model(ids, mask)
        if forward is None and model_name_or_path is not None:
            from transformers import FlaxAutoModel

            hf_model = FlaxAutoModel.from_pretrained(model_name_or_path)
            forward = lambda ids, mask: hf_model(input_ids=ids, attention_mask=mask).last_hidden_state
        if forward is None:
            raise ValueError(
                "BERTScore needs an encoder: pass `user_forward_fn`, `model`, or a local `model_name_or_path`"
                " (this build cannot download pretrained weights)."
            )
        self.forward_fn = forward

        self.add_state("preds_input_ids", [], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", [], dist_reduce_fx="cat")
        self.add_state("target_input_ids", [], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", [], dist_reduce_fx="cat")

    def _tokenize(self, sentences: List[str]) -> Dict[str, np.ndarray]:
        if self.user_tokenizer is not None:
            return self.user_tokenizer(sentences, self.max_length)
        return _simple_whitespace_tokenizer(sentences, self.max_length)

    def update(self, predictions: List[str], references: List[str]) -> None:
        enc_pred = self._tokenize(predictions)
        enc_tgt = self._tokenize(references)
        self.preds_input_ids.append(jnp.asarray(enc_pred["input_ids"]))
        self.preds_attention_mask.append(jnp.asarray(enc_pred["attention_mask"]))
        self.target_input_ids.append(jnp.asarray(enc_tgt["input_ids"]))
        self.target_attention_mask.append(jnp.asarray(enc_tgt["attention_mask"]))

    def compute(self) -> Dict[str, List[float]]:
        pred_ids = np.asarray(dim_zero_cat(self.preds_input_ids))
        pred_mask = np.asarray(dim_zero_cat(self.preds_attention_mask))
        tgt_ids = np.asarray(dim_zero_cat(self.target_input_ids))
        tgt_mask = np.asarray(dim_zero_cat(self.target_attention_mask))

        def _embed(ids, mask):
            outs = []
            for i in range(0, ids.shape[0], self.batch_size):
                outs.append(
                    jnp.asarray(self.forward_fn(jnp.asarray(ids[i:i + self.batch_size]),
                                                jnp.asarray(mask[i:i + self.batch_size])))
                )
            return jnp.concatenate(outs, axis=0)

        pred_emb = _embed(pred_ids, pred_mask)
        tgt_emb = _embed(tgt_ids, tgt_mask)

        pred_w = tgt_w = None
        if self.idf:
            idf_map = _get_tokens_idf(tgt_ids, tgt_mask)
            pred_w = jnp.asarray(_idf_weights(pred_ids, pred_mask, idf_map))
            tgt_w = jnp.asarray(_idf_weights(tgt_ids, tgt_mask, idf_map))

        precision, recall, f1 = _bert_score_from_embeddings(
            pred_emb, jnp.asarray(pred_mask), tgt_emb, jnp.asarray(tgt_mask), pred_w, tgt_w
        )
        return {
            "precision": [float(x) for x in np.asarray(precision)],
            "recall": [float(x) for x in np.asarray(recall)],
            "f1": [float(x) for x in np.asarray(f1)],
        }
