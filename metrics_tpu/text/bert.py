"""BERTScore module metric.

Parity: reference ``torchmetrics/text/bert.py:40`` (update :195 tokenizes and stores
token tensors as cat-states; compute :226 runs the embedding pipeline). The encoder
is pluggable (local HF Flax model / user forward fn) and shares the functional
path's jit-compiled, cached forward + fused scoring (``functional/text/bert.py``).
"""
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from metrics_tpu.functional.text.bert import (
    _apply_baseline,
    _load_baseline_row,
    _resolve_baseline_path,
    _resolve_forward,
    _score_tokenized,
    _simple_whitespace_tokenizer,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat

Array = jax.Array


class BERTScore(Metric):
    """BERTScore: greedy cosine matching of contextual embeddings (P/R/F1 per pair).

    Parity: reference ``text/bert.py:40``. Encoder is pluggable (local HF Flax
    checkpoint, flax module, or a user forward fn) — see ``functional.bert_score``.
    """

    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        model_name_or_path: Optional[str] = None,
        num_layers: Optional[int] = None,
        all_layers: bool = False,
        model: Optional[Any] = None,
        user_tokenizer: Optional[Any] = None,
        user_forward_fn: Optional[Callable] = None,
        verbose: bool = False,
        idf: bool = False,
        max_length: int = 128,
        batch_size: int = 64,
        num_threads: int = 4,
        return_hash: bool = False,
        lang: str = "en",
        rescale_with_baseline: bool = False,
        baseline_path: Optional[str] = None,
        baseline_url: Optional[str] = None,
        mesh: Optional[Any] = None,
        mesh_axis: Any = "dp",
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        self.model_name_or_path = model_name_or_path
        self.max_length = max_length
        self.batch_size = batch_size
        self.idf = idf
        self.user_tokenizer = user_tokenizer
        self.rescale_with_baseline = rescale_with_baseline
        # load at construction so a bad baseline config (missing/malformed csv,
        # out-of-range num_layers) fails fast, and compute() does no file IO
        path = _resolve_baseline_path(rescale_with_baseline, baseline_path, baseline_url)
        self.baseline = _load_baseline_row(path, num_layers) if path is not None else None
        # resolve eagerly: a missing encoder should fail at construction.
        # mesh: the compute()-time encoder forward runs batch-parallel over the
        # mesh's data axis (sharded embedded-model path, parallel/embedded.py)
        self.forward_fn = _resolve_forward(user_forward_fn, model, model_name_or_path, mesh, mesh_axis)

        self.add_state("preds_input_ids", [], dist_reduce_fx="cat")
        self.add_state("preds_attention_mask", [], dist_reduce_fx="cat")
        self.add_state("target_input_ids", [], dist_reduce_fx="cat")
        self.add_state("target_attention_mask", [], dist_reduce_fx="cat")

    def _tokenize(self, sentences: List[str]) -> Dict[str, np.ndarray]:
        if self.user_tokenizer is not None:
            return self.user_tokenizer(sentences, self.max_length)
        return _simple_whitespace_tokenizer(sentences, self.max_length)

    def update(self, predictions: List[str], references: List[str]) -> None:
        enc_pred = self._tokenize(predictions)
        enc_tgt = self._tokenize(references)
        self.preds_input_ids.append(jnp.asarray(enc_pred["input_ids"]))
        self.preds_attention_mask.append(jnp.asarray(enc_pred["attention_mask"]))
        self.target_input_ids.append(jnp.asarray(enc_tgt["input_ids"]))
        self.target_attention_mask.append(jnp.asarray(enc_tgt["attention_mask"]))

    def compute(self) -> Dict[str, List[float]]:
        precision, recall, f1 = _score_tokenized(
            self.forward_fn,
            np.asarray(dim_zero_cat(self.preds_input_ids)),
            np.asarray(dim_zero_cat(self.preds_attention_mask)),
            np.asarray(dim_zero_cat(self.target_input_ids)),
            np.asarray(dim_zero_cat(self.target_attention_mask)),
            idf=self.idf,
            batch_size=self.batch_size,
            # reference contract strips [CLS]/[SEP] from matching (bert.py:324);
            # the whitespace fallback tokenizer adds no special tokens
            strip_special=self.user_tokenizer is not None,
        )
        if self.rescale_with_baseline:
            precision, recall, f1 = _apply_baseline(precision, recall, f1, self.baseline)
        return {
            "precision": [float(x) for x in precision],
            "recall": [float(x) for x in recall],
            "f1": [float(x) for x in f1],
        }
