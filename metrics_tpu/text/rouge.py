"""ROUGEScore module metric.

Parity: reference ``torchmetrics/text/rouge.py:29`` (the reference wraps
nltk/rouge_score; this build computes ROUGE natively — see
``functional/text/rouge.py``).
"""
from typing import Any, Dict, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.rouge import (
    ALLOWED_ROUGE_KEYS,
    _rouge_score_compute,
    _rouge_score_update,
)
from metrics_tpu.metric import Metric
from metrics_tpu.utils.data import dim_zero_cat
from metrics_tpu.utils.imports import _NLTK_AVAILABLE

Array = jax.Array


class ROUGEScore(Metric):
    """ROUGE-N / ROUGE-L scores (native n-gram + LCS implementation, no external deps).

    Example:
        >>> from metrics_tpu import ROUGEScore
        >>> rouge = ROUGEScore()
        >>> scores = rouge(["the cat sat"], ["the cat sat on the mat"])
        >>> print(f"{float(scores['rouge1_fmeasure']):.4f}")
        0.6667
    """
    is_differentiable = False
    higher_is_better = True

    def __init__(
        self,
        use_stemmer: bool = False,
        accumulate: str = "best",
        rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
        **kwargs: Any,
    ) -> None:
        super().__init__(**kwargs)
        if use_stemmer and not _NLTK_AVAILABLE:
            raise ModuleNotFoundError("Stemming requires that `nltk` is installed.")
        self.stemmer = None
        if use_stemmer:
            import nltk

            self.stemmer = nltk.stem.porter.PorterStemmer()

        if isinstance(rouge_keys, str):
            rouge_keys = (rouge_keys,)
        for key in rouge_keys:
            if key not in ALLOWED_ROUGE_KEYS:
                raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {ALLOWED_ROUGE_KEYS}")
        self.rouge_keys = rouge_keys
        self.rouge_keys_values = [
            int(key[5:]) if key[5:].isdigit() else key[5:] for key in rouge_keys
        ]
        if accumulate not in ("best", "avg"):
            raise ValueError(f"Got unknown accumulate method {accumulate}. Expected 'best' or 'avg'.")
        self.accumulate = accumulate
        for key in self.rouge_keys_values:
            for score_type in ("fmeasure", "precision", "recall"):
                self.add_state(f"rouge{key}_{score_type}", [], dist_reduce_fx="cat")

    def update(self, preds: Union[str, Sequence[str]], targets: Union[str, Sequence[str]]) -> None:
        preds = [preds] if isinstance(preds, str) else list(preds)
        targets = [targets] if isinstance(targets, str) else list(targets)
        results = _rouge_score_update(preds, targets, self.rouge_keys_values, self.accumulate, self.stemmer)
        for key, scores in results.items():
            for score in scores:
                for score_type, value in score.items():
                    getattr(self, f"rouge{key}_{score_type}").append(jnp.reshape(value, (1,)))

    def compute(self) -> Dict[str, Array]:
        update_output = {}
        for key in self.rouge_keys_values:
            for score_type in ("fmeasure", "precision", "recall"):
                vals = getattr(self, f"rouge{key}_{score_type}")
                update_output[f"rouge{key}_{score_type}"] = [dim_zero_cat(vals)] if vals else []
        return _rouge_score_compute(update_output)
