"""MatchErrorRate module metric.

Parity: reference ``torchmetrics/text/mer.py:24``.
"""
from typing import Any, List, Union

import jax
import jax.numpy as jnp

from metrics_tpu.functional.text.mer import _mer_compute, _mer_update
from metrics_tpu.metric import Metric

Array = jax.Array


class MatchErrorRate(Metric):
    """Match error rate (word edits / (edits + hits)).

    Example:
        >>> from metrics_tpu import MatchErrorRate
        >>> metric = MatchErrorRate()
        >>> score = metric(['hello there world'], ['hello there word'])
        >>> print(f"{float(score):.4f}")
        0.3333
    """
    is_differentiable = False
    higher_is_better = False

    def __init__(self, **kwargs: Any) -> None:
        super().__init__(**kwargs)
        self.add_state("errors", jnp.asarray(0.0), dist_reduce_fx="sum")
        self.add_state("total", jnp.asarray(0.0), dist_reduce_fx="sum")

    def update(self, predictions: Union[str, List[str]], references: Union[str, List[str]]) -> None:
        errors, total = _mer_update(predictions, references)
        self.errors = self.errors + errors
        self.total = self.total + total

    def compute(self) -> Array:
        return _mer_compute(self.errors, self.total)
